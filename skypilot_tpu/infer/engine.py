"""KV-cache autoregressive inference engine (prefill/decode split).

JetStream-style serving loop, TPU-first:

  - **prefill**: one jitted full-prompt forward writes K/V into a
    static-shape cache [B, kv_heads, max_seq_len, head_dim] per layer
    (models/llama.py `_cached_attention`) — large matmuls, MXU-bound.
    Prompts are right-padded to bucket multiples so the set of compiled
    prefill shapes is small and the readiness warmup is honest;
  - **decode**: ONE jitted step per generated token that fuses
    sampling, the kv-mask slot write, and the forward — the host loop
    only fetches the sampled ids (needed for output/eos anyway);
  - ragged batches share one batch via the [B, max_seq_len] kv-mask, so
    rows of different lengths can't cross-contaminate (verified against
    cache-free re-forwarding in tests/unit_tests/test_infer.py);
  - params are served in bf16 by default (no optimizer here; f32 master
    weights are a training concern), sharded over a mesh when given,
    and loadable from a trainer Orbax checkpoint (the bucket-checkpoint
    contract, train/checkpoint.py).

The reference's serving path is an external vLLM container
(`llm/qwen/serve-110b.yaml` — SURVEY.md §2.11); this engine is the
framework-native replacement that SkyServe replicas run
(infer/server.py).
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import queue as queue_lib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import skypilot_tpu.models as models_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.infer import failures
from skypilot_tpu.observability import ledger as ledger_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing as tracing_lib
from skypilot_tpu.parallel import sharding as sharding_lib
from skypilot_tpu.utils import chaos

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0     # 0 => greedy
    top_k: int = 0               # 0 => disabled
    top_p: float = 1.0           # 1 => disabled
    eos_id: Optional[int] = None
    max_new_tokens: int = 64
    # Per-request reproducibility: with a seed set, the sampled draw
    # uses per-row keys folding (seed, generated_index) — independent
    # of which other requests share the decode batch or when the
    # request was admitted.  (Exact to compiled-graph numerics: batch
    # companions can shift the kv-read-bucket compile and thus
    # last-bit logits; at a near-tie that can still flip a token.)
    # The request-level engine seeds the whole generate() call.
    seed: Optional[int] = None


def sample_logits(logits: jax.Array, rng: jax.Array,
                  config: SamplingConfig) -> jax.Array:
    """Sample token ids [B] from logits [B, V] (one shared config —
    delegates to the batched per-row-temperature kernel)."""
    temps = jnp.full((logits.shape[0],), config.temperature,
                     jnp.float32)
    return sample_logits_batched(logits, rng, temps, config.top_k,
                                 config.top_p)


def sample_logits_rows(logits: jax.Array, keys: jax.Array,
                       temps: jax.Array, top_ks: jax.Array,
                       top_ps: jax.Array, *, max_k: int,
                       use_top_p: bool,
                       top_p_in_topk: bool = False) -> jax.Array:
    """Per-row sampling [B, V] -> [B] with one PRNG key per row: rows
    with temp<=0 decode greedily, the rest sample — one jit for a
    continuous batch whose slots carry different requests' sampling
    configs AND seeds.

    `top_ks` [B] int32 and `top_ps` [B] f32 are TRACED, so greedy,
    top-k and top-p requests share one compiled step; only the coarse
    capability keys are static: `max_k` (0 = no top-k path compiled;
    otherwise a power-of-two bucket >= every row's k, so the kernel's
    lax.top_k width — and the compile cache — is bounded by log2(V)
    buckets, not by the number of distinct user k values) and
    `use_top_p` (skips the full-vocab sort when nobody asked for
    nucleus sampling).  A row's k-th-largest threshold is exact for
    any bucket >= k, so bucketing never changes the sampled
    distribution.

    `top_p_in_topk` (static): the caller promises every row with
    top_ps < 1.0 also has top_ks > 0.  Then every logit a nucleus
    cutoff could keep already sits in the descending `vals` from
    lax.top_k, so the [B, max_k] window replaces the full-vocab
    `jnp.sort` — O(V log V) -> O(V log k) per step.  Identical
    numerics: dropped entries are -1e30 in both formulations and
    contribute exactly-zero softmax mass, and rows with top_ks <= 0
    (possible only with top_ps >= 1.0 under the promise) take the
    keep-all branch of the cutoff."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = filter_logits_rows(logits, temps, top_ks, top_ps,
                                max_k=max_k, use_top_p=use_top_p,
                                top_p_in_topk=top_p_in_topk)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row))(
            keys, scaled).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


def filter_logits_rows(logits: jax.Array, temps: jax.Array,
                       top_ks: jax.Array, top_ps: jax.Array, *,
                       max_k: int, use_top_p: bool,
                       top_p_in_topk: bool = False) -> jax.Array:
    """The per-row temperature/top-k/top-p filter sample_logits_rows
    draws from, exposed on its own: returns the temperature-scaled
    logits with every filtered-out entry at -1e30, i.e. softmax of the
    return value IS the decode-time sampling distribution.  The
    speculative acceptance kernel (infer/speculative.py) scores draft
    proposals against exactly this distribution, which is what makes
    its accept/resample rule distribution-preserving."""
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    scaled = logits / safe
    if max_k > 0:
        vals = jax.lax.top_k(scaled, max_k)[0]        # [B, max_k] desc
        idx = jnp.clip(top_ks - 1, 0, max_k - 1)[:, None]
        kth = jnp.take_along_axis(vals, idx, axis=-1)  # [B, 1]
        keep = (top_ks[:, None] <= 0) | (scaled >= kth)
        scaled = jnp.where(keep, scaled, -1e30)
    if use_top_p:
        if top_p_in_topk and max_k > 0:
            # The surviving support is each row's first top_ks entries
            # of `vals` (already descending); the -1e30 tail keeps the
            # order sorted and carries zero probability mass.
            sorted_logits = jnp.where(
                jnp.arange(max_k)[None, :] < top_ks[:, None], vals,
                -1e30)
        else:
            sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_ps[:, None], axis=-1,
                             keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        keep = (top_ps[:, None] >= 1.0) | (scaled >= cutoff)
        scaled = jnp.where(keep, scaled, -1e30)
    return scaled


def top_k_bucket(k: int, vocab_size: int) -> int:
    """Static lax.top_k width for a batch whose largest row k is `k`:
    the next power of two, capped at the vocab (0 stays 0 — the top-k
    path is compiled out entirely)."""
    if k <= 0:
        return 0
    b = 1
    while b < k:
        b *= 2
    return min(b, vocab_size)


def sample_logits_batched(logits: jax.Array, rng: jax.Array,
                          temps: jax.Array, top_k: int,
                          top_p: float) -> jax.Array:
    """Shared-rng, shared-config variant (request-level engine): rows
    draw from per-row splits of one key."""
    keys = jax.random.split(rng, logits.shape[0])
    b = logits.shape[0]
    return sample_logits_rows(
        logits, keys, temps,
        jnp.full((b,), top_k, jnp.int32),
        jnp.full((b,), top_p, jnp.float32),
        max_k=top_k_bucket(top_k, logits.shape[-1]),
        use_top_p=top_p < 1.0,
        top_p_in_topk=top_k > 0)


_QUANT_KEYS = frozenset(('q8', 'scale'))


def quantize_params_int8(params: Any) -> Any:
    """Weight-only int8: matmul kernels and token embeddings become
    {'q8': int8, 'scale': f32} with per-output-channel scales (absmax
    over the leaf's FIRST axis — its input/vocab axis; quantized
    serving forces scan_layers=False so no leaf carries a leading
    layer axis).  Halves the param bytes decode must stream from HBM —
    the dominant cost of TPU decode — with dequant fused into each
    consumer.  Biases/norms/rope tables stay float."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    out = {}
    for key, x in flat.items():
        x = jnp.asarray(x)
        name = str(key[-1])
        if (name == 'kernel' or name == 'tok_embed') and x.ndim >= 2 \
                and jnp.issubdtype(x.dtype, jnp.floating):
            scale = jnp.max(jnp.abs(x), axis=0, keepdims=True) / 127.0
            scale = jnp.maximum(scale, 1e-8).astype(jnp.float32)
            q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                         -127, 127).astype(jnp.int8)
            out[key + ('q8',)] = q
            out[key + ('scale',)] = scale
        else:
            out[key] = x
    return flax.traverse_util.unflatten_dict(out)


def unstack_scanned_params(params: Any, n_layers: int) -> Any:
    """Scanned-layer params ('layers' subtree with a leading [L] axis,
    how the trainer saves them by default) -> the unscanned layout
    ('layer_i' subtrees) that quantized serving uses."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    out = {}
    for key, x in flat.items():
        if key[0] == 'layers':
            for i in range(n_layers):
                out[(f'layer_{i}',) + key[1:]] = x[i]
        else:
            out[key] = x
    return flax.traverse_util.unflatten_dict(out)


def _is_quant_leaf(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == _QUANT_KEYS


def quantized_param_shardings(mesh, float_shardings: Any,
                              quantized_params: Any) -> Any:
    """Shardings for the quantize_params_int8 layout, derived from the
    SAME logical rules as the float kernels: `q8` keeps its kernel's
    NamedSharding verbatim (same shape, same partitioning); `scale`
    (shape [1, *out_dims] — absmax over the input axis) drops the
    now-size-1 first axis from the spec and keeps the output-axis
    partitioning, so each tensor-parallel shard holds exactly the
    scales of its own output channels."""
    import flax

    flat_q = flax.traverse_util.flatten_dict(quantized_params)
    flat_s = flax.traverse_util.flatten_dict(float_shardings)
    out = {}
    for key in flat_q:
        if key[-1] == 'q8':
            out[key] = flat_s[key[:-1]]
        elif key[-1] == 'scale' and key[:-1] in flat_s:
            base_spec = tuple(flat_s[key[:-1]].spec)
            out[key] = NamedSharding(
                mesh, P(None, *base_spec[1:]))
        else:
            out[key] = flat_s[key]
    return flax.traverse_util.unflatten_dict(out)


def maybe_dequantize_params(params: Any, dtype: Any) -> Any:
    """Inverse of quantize_params_int8, run INSIDE the jitted forward
    so the int8 weights are what lives in (and streams from) HBM."""
    return jax.tree.map(
        lambda leaf: (leaf['q8'].astype(jnp.float32)
                      * leaf['scale']).astype(dtype)
        if _is_quant_leaf(leaf) else leaf,
        params, is_leaf=_is_quant_leaf)


def paged_pool_mode(tensor: int, kvh: int, n_pages: int,
                    page_size: int) -> str:
    """How the paged K/V/scale pools shard over a `tensor=N` mesh —
    the single source both `_cache_sharding` (which builds the specs)
    and `sharding_info()` (which reports them) derive from.

      kv_heads — the fast path: pools split on the kv-head axis,
                 matching the attention head sharding, so the fused
                 kernel's per-shard block-table walk needs no
                 collective (head order is kv_head-major).
      pages    — kvh doesn't divide (DeepSeek's absorbed-latent pool
                 has kvh == 1): split the page axis; gathers become
                 GSPMD all-gathers.
      sequence — neither divides (n_pages = B*pps + 1 is odd by
                 construction): split within-page positions.
      replicated — nothing divides; every chip holds the full pool.
    """
    if tensor <= 1:
        return 'unsharded'
    if kvh and kvh % tensor == 0:
        return 'kv_heads'
    if n_pages and n_pages % tensor == 0:
        return 'pages'
    if page_size and page_size % tensor == 0:
        return 'sequence'
    return 'replicated'


def _cache_sharding(mesh, leaf, n_pages: int = 0) -> NamedSharding:
    """KV caches shard their kv-heads dim over `tensor` (matching the
    attention head sharding); scalars/cursors/block tables replicate.
    Leaf shapes: [B, kvh, S, hd] contiguous / [n_pages, kvh, ps, hd]
    paged pool unscanned, [L, B, kvh, S, hd] / [L, n_pages, kvh, ps,
    hd] scanned.  Paged pool leaves (recognized by `n_pages` on the
    leading pool axis) fall back to page- then sequence-axis sharding
    when the kv-head axis doesn't divide (paged_pool_mode) — the
    DeepSeek latent kvh==1 geometry stays sharded instead of silently
    replicating the whole pool on every chip."""
    tensor = max(mesh.shape.get('tensor', 1), 1)
    if leaf.ndim == 4 and leaf.shape[1] % tensor == 0:
        return NamedSharding(mesh, P(None, 'tensor', None, None))
    if leaf.ndim == 5 and leaf.shape[2] % tensor == 0:
        return NamedSharding(mesh, P(None, None, 'tensor', None, None))
    if n_pages and leaf.ndim == 4 and leaf.shape[0] == n_pages:
        mode = paged_pool_mode(tensor, leaf.shape[1], n_pages,
                               leaf.shape[2])
        if mode == 'pages':
            return NamedSharding(mesh, P('tensor', None, None, None))
        if mode == 'sequence':
            return NamedSharding(mesh, P(None, None, 'tensor', None))
    if n_pages and leaf.ndim == 5 and leaf.shape[1] == n_pages:
        mode = paged_pool_mode(tensor, leaf.shape[2], n_pages,
                               leaf.shape[3])
        if mode == 'pages':
            return NamedSharding(mesh,
                                 P(None, 'tensor', None, None, None))
        if mode == 'sequence':
            return NamedSharding(mesh,
                                 P(None, None, None, 'tensor', None))
    return NamedSharding(mesh, P())


def resolve_decode_kernel(decode_kernel: str, *, on_tpu: bool,
                          page_size: int, tensor: int = 1,
                          pool_kvh: Optional[int] = None
                          ) -> Tuple[str, bool]:
    """Resolve the --decode-kernel request to (kernel, interpret) —
    the full table, deterministic, validated at startup so a bad
    combination is a ValueError here and never a Pallas partitioning
    crash mid-serve.

    'auto' picks the fused Pallas kernel only where it is actually
    lowered: on TPU, paged cache, and — under a tensor>1 mesh — only
    when the pool kv-head axis divides (the shard_map lowering walks
    per-shard kv-heads; the kvh==1 latent fallback shards pages/
    positions instead, which only the XLA gather path handles).
    Off-TPU the fused kernel runs in the orders-of-magnitude-slower
    interpreter, so only an explicit 'fused' (tests, parity benches)
    ever selects it there."""
    if decode_kernel not in ('auto', 'fused', 'xla'):
        raise ValueError(
            f"decode_kernel must be 'auto', 'fused' or 'xla', "
            f'got {decode_kernel!r}')
    sharded_ok = (tensor <= 1
                  or (pool_kvh or 0) % tensor == 0)
    if decode_kernel == 'auto':
        decode_kernel = 'fused' if (on_tpu and page_size
                                    and sharded_ok) else 'xla'
    elif decode_kernel == 'fused':
        if not page_size:
            raise ValueError(
                "decode_kernel='fused' requires a paged KV cache "
                '(page_size > 0)')
        if not sharded_ok:
            raise ValueError(
                f"decode_kernel='fused' needs the pool kv-head axis "
                f'({pool_kvh}) divisible by the tensor mesh axis '
                f'({tensor}); this geometry falls back to page-/'
                "sequence-sharded pools, which only "
                "decode_kernel='xla' supports")
    return decode_kernel, (decode_kernel == 'fused' and not on_tpu)


def decode_cache_read_bytes(abstract_cache: Any, n_heads: int,
                            context: Optional[int] = None,
                            page_size: int = 0,
                            row_contexts: Optional[Sequence[int]] = None,
                            decode_kernel: str = 'xla'
                            ) -> Dict[str, float]:
    """Per-decode-step KV-cache read traffic estimate (HBM bytes).

    Walks the cache pytree (K/V leaves are [B, kvh, S, hd] unscanned or
    [L, B, kvh, S, hd] scanned; cursor/scalar leaves are skipped) and
    sums the bytes one decode step streams from HBM:

      - ``grouped_bytes``: what the grouped-einsum epilogue
        (ops/grouped_attention.py) reads — each cache row once, at its
        stored kvh head count;
      - ``repeat_bytes``: what the old repeat-then-matmul epilogue
        forced — every row materialized n_heads // kvh times so each
        query head could matmul its own copy.

    ``context`` caps the read length per row (a half-full cache reads
    half the bytes); None charges the full static S.  The ratio
    ``repeat_bytes / grouped_bytes`` is the h-fold bandwidth win the
    grouped path keeps: n_heads/kvh per GQA leaf, n_heads for a
    DeepSeek absorbed latent cache (kvh == 1).

    With kv_cache_dtype='int8' the K/V leaves arrive as int8
    (itemsize 1, half/quarter of bf16/f32) and the per-(kv-head,
    position) f32 scale leaves [B, kvh, S, 1] walk the SAME ndim-4/5
    dispatch with hd == 1 — so the reported bytes charge the
    quantized rows PLUS the scale reads, keeping the int8-vs-float
    comparison honest (per position: 2*hd + 2*4 bytes vs
    2*hd*itemsize).

    With ``page_size`` > 0 the cache is PAGED: K/V pool leaves are
    [n_pages, kvh, page_size, hd] ([L, n_pages, ...] scanned) and a
    decode step gathers only the pages a row has allocated, so the
    charge is per-ROW: ``row_contexts`` (required) gives each live
    row's context length, each charged ceil(ctx / page_size) pages of
    page_size positions — reads track live context, not max_seq_len.
    ``context`` still caps every row (the bucketed read high-water
    mark).  Block tables / cursors (ndim <= 3 int32) are skipped as
    negligible next to the K/V stream.

    ``epilogue_bytes`` charges what the POOL reads alone undercount on
    the paged XLA path (``decode_kernel='xla'``): gather_pages writes a
    contiguous [B, kvh, n_read*ps, d] copy of every pool leaf (K, V,
    and the int8 scale siblings) that the grouped einsum then re-reads
    — 2x the gathered size, for EVERY row at the shared bucketed
    window (the widest row's page-rounded context, further capped by
    ``context``), live or not.  The fused Pallas kernel
    (``decode_kernel='fused'``) streams pool tiles straight into VMEM,
    so its epilogue term is exactly 0 — the delta the kernel removes.
    ``total_bytes`` = grouped + epilogue, the honest per-step figure.
    """
    grouped = 0
    repeated = 0
    if decode_kernel not in ('fused', 'xla'):
        raise ValueError(
            f"decode_kernel must be 'fused' or 'xla', got "
            f'{decode_kernel!r}')
    if page_size > 0:
        if row_contexts is None:
            raise ValueError(
                'row_contexts is required for paged accounting '
                '(page_size > 0): per-row live context lengths.')
        positions = 0
        window = 0
        for ctx in row_contexts:
            if context is not None:
                ctx = min(ctx, context)
            row_pos = -(-max(int(ctx), 0) // page_size) * page_size
            positions += row_pos
            window = max(window, row_pos)
        epilogue = 0
        for leaf in jax.tree.leaves(abstract_cache):
            if leaf.ndim == 4:       # [n_pages, kvh, ps, hd]
                layers, (_, kvh, ps, hd) = 1, leaf.shape
            elif leaf.ndim == 5:     # [L, n_pages, kvh, ps, hd]
                layers, _, kvh, ps, hd = leaf.shape
            else:
                continue             # block tables / cursors
            itemsize = np.dtype(leaf.dtype).itemsize
            leaf_bytes = layers * kvh * positions * hd * itemsize
            grouped += leaf_bytes
            repeated += leaf_bytes * max(1, n_heads // kvh)
            if decode_kernel == 'xla':
                # Write + re-read of the gathered contiguous copy,
                # every row at the shared read window.
                epilogue += 2 * layers * kvh * (
                    len(row_contexts) * window) * hd * itemsize
        return {
            'grouped_bytes': float(grouped),
            'repeat_bytes': float(repeated),
            'epilogue_bytes': float(epilogue),
            'total_bytes': float(grouped + epilogue),
            'reduction': float(repeated) / float(grouped)
            if grouped else 1.0,
        }
    for leaf in jax.tree.leaves(abstract_cache):
        if leaf.ndim == 4:
            layers, (b, kvh, s, hd) = 1, leaf.shape
        elif leaf.ndim == 5:
            layers, b, kvh, s, hd = leaf.shape
        else:
            continue  # cursors / scalars: not streamed per step
        read_len = s if context is None else min(context, s)
        itemsize = np.dtype(leaf.dtype).itemsize
        leaf_bytes = layers * b * kvh * read_len * hd * itemsize
        grouped += leaf_bytes
        repeated += leaf_bytes * max(1, n_heads // kvh)
    return {
        'grouped_bytes': float(grouped),
        'repeat_bytes': float(repeated),
        'epilogue_bytes': 0.0,       # contiguous reads need no gather
        'total_bytes': float(grouped),
        'reduction': float(repeated) / float(grouped) if grouped else 1.0,
    }


def resolve_kernels(decode_kernel: str = 'auto',
                    prefill_kernel: str = 'auto', *, on_tpu: bool,
                    page_size: int, tensor: int = 1,
                    pool_kvh: Optional[int] = None
                    ) -> Dict[str, Tuple[str, bool]]:
    """Resolve BOTH attention-kernel requests to {'decode': (kernel,
    interpret), 'prefill': (kernel, interpret)} — one deterministic
    table, validated at startup so a bad combination is a ValueError
    here and never a Pallas crash mid-serve.

    The prefill column mirrors the decode column's rules:
    'auto' = fused on TPU iff the engine is paged (the ragged-prefill
    kernel tiles the contiguous prefill cache at the page granularity,
    so it only exists where a page geometry does) and — under a
    tensor>1 mesh — only when the cache kv-head axis divides the mesh
    axis (its shard_map lowering walks per-shard kv-heads, exactly
    like the decode kernel's).  'xla' (the sliced-prefix grouped
    einsum) is the permanent fallback and the parity oracle;
    explicitly requesting 'fused' off-TPU runs the interpreter
    (tests/benches only)."""
    decode = resolve_decode_kernel(decode_kernel, on_tpu=on_tpu,
                                   page_size=page_size, tensor=tensor,
                                   pool_kvh=pool_kvh)
    if prefill_kernel not in ('auto', 'fused', 'xla'):
        raise ValueError(
            f"prefill_kernel must be 'auto', 'fused' or 'xla', "
            f'got {prefill_kernel!r}')
    sharded_ok = (tensor <= 1
                  or (pool_kvh or 0) % tensor == 0)
    if prefill_kernel == 'auto':
        prefill_kernel = 'fused' if (on_tpu and page_size
                                     and sharded_ok) else 'xla'
    elif prefill_kernel == 'fused':
        if not page_size:
            raise ValueError(
                "prefill_kernel='fused' requires a paged KV cache "
                '(page_size > 0): the ragged-prefill kernel walks the '
                'prefill cache as logical pages')
        if not sharded_ok:
            raise ValueError(
                f"prefill_kernel='fused' needs the cache kv-head axis "
                f'({pool_kvh}) divisible by the tensor mesh axis '
                f'({tensor}); this geometry must use '
                "prefill_kernel='xla'")
    return {
        'decode': decode,
        'prefill': (prefill_kernel,
                    prefill_kernel == 'fused' and not on_tpu),
    }


def prefill_cache_read_bytes(abstract_cache1: Any, n_heads: int,
                             context: int,
                             prefill_kernel: str = 'xla'
                             ) -> Dict[str, float]:
    """Per-chunk prefill read-traffic estimate (HBM bytes) over the
    CONTIGUOUS batch-1 prefill cache — the prefill twin of
    decode_cache_read_bytes, so bench JSON and skytpu_prefill_* series
    count the cost that was previously invisible.

    ``context`` is the chunk's bucketed read window (the engine's
    kv_read_bucket high-water mark; see models/llama.py).  Per K/V
    leaf (int8 scale siblings walk the same ndim dispatch):

      - ``grouped_bytes``: the live prefix streamed once —
        layers * b * kvh * read_len * hd * itemsize;
      - ``epilogue_bytes``: what the XLA path pays ON TOP — the
        ``cached_k.value[:, :, :read_len]`` slice materialized as a
        contiguous copy feeding the grouped einsum, written then
        re-read (2x the window), exactly the decode-epilogue
        convention.  The fused ragged-prefill kernel streams
        page-shaped cache tiles straight into VMEM, so its epilogue
        term is exactly 0 — the delta the kernel removes;
      - ``total_bytes`` = grouped + epilogue.
    """
    if prefill_kernel not in ('fused', 'xla'):
        raise ValueError(
            f"prefill_kernel must be 'fused' or 'xla', got "
            f'{prefill_kernel!r}')
    grouped = 0
    repeated = 0
    epilogue = 0
    for leaf in jax.tree.leaves(abstract_cache1):
        if leaf.ndim == 4:           # [B, kvh, S, hd]
            layers, (b, kvh, s, hd) = 1, leaf.shape
        elif leaf.ndim == 5:         # [L, B, kvh, S, hd]
            layers, b, kvh, s, hd = leaf.shape
        else:
            continue                 # cursors / scalars
        read_len = min(max(int(context), 0), s)
        itemsize = np.dtype(leaf.dtype).itemsize
        leaf_bytes = layers * b * kvh * read_len * hd * itemsize
        grouped += leaf_bytes
        repeated += leaf_bytes * max(1, n_heads // kvh)
        if prefill_kernel == 'xla':
            epilogue += 2 * leaf_bytes
    return {
        'grouped_bytes': float(grouped),
        'repeat_bytes': float(repeated),
        'epilogue_bytes': float(epilogue),
        'total_bytes': float(grouped + epilogue),
        'reduction': float(repeated) / float(grouped)
        if grouped else 1.0,
    }


# Paged-pool leaf names (models/llama.py _paged_slot_attention) and
# the batch-1 contiguous prefill-cache leaves they are fed from.
_POOL_OF_CONTIG = {
    'cached_key': 'page_key',
    'cached_value': 'page_value',
    'cached_key_scale': 'page_key_scale',
    'cached_value_scale': 'page_value_scale',
}
_CONTIG_OF_POOL = {v: k for k, v in _POOL_OF_CONTIG.items()}


def _path_names(path) -> tuple:
    """Pytree key path -> plain name tuple (DictKey et al. -> str)."""
    return tuple(getattr(k, 'key', str(k)) for k in path)


# -- slot-cache insert/clear builders -----------------------------------
# Shared by ContinuousBatchingEngine and the speculative draft runner
# (infer/speculative.py), whose private cache mirrors the target's slot
# layout: the functions are generic over the cache pytree, so one
# definition serves both models.

def make_insert_fn():
    """Build the contiguous slot-insert: write a freshly prefilled
    request into slot `slot` — cache rows, last-logits row, kv_mask
    row.  `slot` is a traced scalar, so one compile covers every
    slot."""
    def _insert(cache, last, kv_mask, cache1, last_row, mask_row,
                slot):
        def _ins(big, small):
            if big.ndim == 4:      # [B, kvh, S, hd]
                return jax.lax.dynamic_update_slice(
                    big, small, (slot, 0, 0, 0))
            if big.ndim == 5:      # scanned: [L, B, kvh, S, hd]
                return jax.lax.dynamic_update_slice(
                    big, small, (0, slot, 0, 0, 0))
            return big             # cursor scalars: unused in slot mode
        cache = jax.tree.map(_ins, cache, cache1)
        last = jax.lax.dynamic_update_slice(
            last, last_row[None], (slot, 0))
        kv_mask = jax.lax.dynamic_update_slice(
            kv_mask, mask_row[None], (slot, 0))
        return cache, last, kv_mask
    return _insert


def make_paged_insert_fn(ps: int, pps: int):
    """Build the paged twin of the contiguous insert: scatter the
    batch-1 contiguous prefill cache into the slot's pool pages and
    write its device block-table row.  Pages below `copy_start_page`
    hold a SHARED prefix that is already in the pool — their writes
    are redirected to the reserved null page 0 so a refcounted page is
    never rewritten."""
    def _insert_paged(cache, last, kv_mask, cache1, last_row,
                      mask_row, table_row, slot, copy_start_page):
        flat1 = {
            _path_names(p_): leaf for p_, leaf in
            jax.tree_util.tree_flatten_with_path(cache1)[0]}
        phys = jnp.where(
            jnp.arange(pps) >= copy_start_page, table_row, 0)

        def _scatter(path, pool):
            names = _path_names(path)
            src_name = _CONTIG_OF_POOL.get(names[-1])
            if src_name is not None:
                src = flat1[names[:-1] + (src_name,)]
                if pool.ndim == 4:
                    # pool [n_pages, kvh, ps, d], src [1, kvh, S, d]
                    kvh, _, d = src.shape[1:]
                    content = src[0].reshape(kvh, pps, ps, d)
                    content = jnp.transpose(content, (1, 0, 2, 3))
                    return pool.at[phys].set(
                        content.astype(pool.dtype))
                # scanned: pool [L, n_pages, kvh, ps, d],
                #          src  [L, 1, kvh, S, d]
                L = src.shape[0]
                kvh, _, d = src.shape[2:]
                content = src[:, 0].reshape(L, kvh, pps, ps, d)
                content = jnp.transpose(content, (0, 2, 1, 3, 4))
                return pool.at[:, phys].set(
                    content.astype(pool.dtype))
            if names[-1] == 'block_table':
                if pool.ndim == 2:      # [B, pps]
                    return jax.lax.dynamic_update_slice(
                        pool, table_row[None], (slot, 0))
                row = jnp.broadcast_to(  # scanned [L, B, pps]
                    table_row[None, None],
                    (pool.shape[0], 1, pool.shape[2]))
                return jax.lax.dynamic_update_slice(
                    pool, row, (0, slot, 0))
            return pool          # cursors: unused in slot mode

        cache = jax.tree_util.tree_map_with_path(_scatter, cache)
        last = jax.lax.dynamic_update_slice(
            last, last_row[None], (slot, 0))
        kv_mask = jax.lax.dynamic_update_slice(
            kv_mask, mask_row[None], (slot, 0))
        return cache, last, kv_mask
    return _insert_paged


def make_clear_table_fn():
    """Build the dead-slot block-table clear: the slot-mode write path
    scatters into table[row, cursor] even for inactive rows, and a
    stale row would scribble on pages the allocator already handed
    elsewhere.  The zeroed row points at the reserved null page."""
    def _clear_table(cache, slot):
        def _clr(path, leaf):
            if _path_names(path)[-1] != 'block_table':
                return leaf
            if leaf.ndim == 2:
                zero = jnp.zeros((1, leaf.shape[1]), leaf.dtype)
                return jax.lax.dynamic_update_slice(
                    leaf, zero, (slot, 0))
            zero = jnp.zeros(
                (leaf.shape[0], 1, leaf.shape[2]), leaf.dtype)
            return jax.lax.dynamic_update_slice(
                leaf, zero, (0, slot, 0))
        return jax.tree_util.tree_map_with_path(_clr, cache)
    return _clear_table


def make_set_table_fn():
    """Build the mixed-prefill slot reservation: write a slot's device
    block-table row (and nothing else) so subsequent mixed decode
    steps scatter the row's prefill chunks straight into its pool
    pages — the mixed path has no batch-1 staging cache to insert
    from.  The row arrives 0-filled past the allocated prefix, so
    out-of-range writes land on the reserved null page."""
    def _set_table(cache, table_row, slot):
        def _set(path, leaf):
            if _path_names(path)[-1] != 'block_table':
                return leaf
            if leaf.ndim == 2:      # [B, pps]
                return jax.lax.dynamic_update_slice(
                    leaf, table_row[None], (slot, 0))
            row = jnp.broadcast_to(  # scanned [L, B, pps]
                table_row[None, None],
                (leaf.shape[0], 1, leaf.shape[2]))
            return jax.lax.dynamic_update_slice(
                leaf, row, (0, slot, 0))
        return jax.tree_util.tree_map_with_path(_set, cache)
    return _set_table


def make_page_write_fn():
    """Build the host-tier rehydration writer: scatter ONE page's
    leaves (host arrays uploaded as jit args) into every pool leaf at
    a traced page id — a single compile covers any page.  Donates the
    shared cache like the other pool mutators."""
    def _page_write(cache, page, updates):
        def _upd(path, leaf):
            names = _path_names(path)
            if names[-1] not in _CONTIG_OF_POOL:
                return leaf
            arr = updates['/'.join(str(n) for n in names)]
            if leaf.ndim == 4:        # [n_pages, kvh, ps, hd]
                return leaf.at[page].set(arr.astype(leaf.dtype))
            return leaf.at[:, page].set(  # scanned [L, n_pages, ...]
                arr.astype(leaf.dtype))
        return jax.tree_util.tree_map_with_path(_upd, cache)
    return _page_write


@dataclasses.dataclass
class _Slot:
    """Host-side state of one occupied decode slot."""
    request_id: int
    prompt_len: int           # true prompt length (rope base)
    pad_len: int              # bucketed prefill length (cache cursor base)
    max_new: int
    eos_id: Optional[int]
    temperature: float
    top_k: int
    top_p: float
    seed: int = 0
    generated: int = 0
    # Decode/verify steps this slot took part in — diverges from
    # generated on a speculating engine (multi-token commits), and the
    # per-request tokens_per_step trace field derives from it.
    steps: int = 0
    # Global engine step indices of this slot's first/last token
    # commits — stamped at consume time, handed to the request trace
    # at completion so /traces?id= joins against the step ledger.
    first_step_idx: Optional[int] = None
    last_step_idx: Optional[int] = None
    outputs: List[int] = dataclasses.field(default_factory=list)
    # Paged cache only: this slot's allocated page ids (block-table
    # prefix), released back to the allocator on completion/eviction.
    pages: List[int] = dataclasses.field(default_factory=list)
    # Self-drafting speculation only: the true prompt ids, kept so the
    # n-gram proposer can match against prompt + outputs.
    prompt_ids: Optional[List[int]] = None
    # Handoff-admitted slots: the first `pre_emitted` committed tokens
    # were already streamed to the client by the prefill-role replica
    # (the seed token), so _commit_token appends them for eos/budget
    # accounting but does NOT push them to the stream queue — the
    # relayed stream stays byte-identical to a single-replica run.
    pre_emitted: int = 0


@dataclasses.dataclass
class _PendingPrefill:
    """A reserved slot whose prompt is being prefilled in chunks —
    one chunk per scheduler tick, so live slots keep decoding instead
    of stalling for a long prompt's whole prefill."""
    slot_idx: int
    rid: int
    cfg: SamplingConfig
    true_len: int
    pad: int
    tokens: Any               # np [1, pad]
    mask_row: Any             # np [max_seq]
    cache1: Any
    done: int = 0
    last_row: Any = None      # logits at the prompt's last true token
    # Paged cache only:
    pages: List[int] = dataclasses.field(default_factory=list)
    table_row: Any = None     # np [pages_per_slot] int32 (0-filled tail)
    shared_len: int = 0       # prefix positions already in the pool
    # Mixed-batch prefill (prefill_mix_budget > 0): the prompt's
    # chunks ride DECODE steps (no batch-1 staging cache; cache1 is
    # None), writing straight into the slot's shared-cache row /
    # pool pages.  `seed` is the request's resolved sampling seed,
    # fixed at admission so the in-graph seeding draw and the slot's
    # later decode draws fold the same key.
    mixed: bool = False
    seed: int = 0
    # Handoff admission (role='decode'): the "prefill" arrived as a
    # wire artifact — cache1 was rebuilt from shipped tensors, done is
    # already pad, and the slot must mark its seed token pre-emitted.
    handoff: bool = False
    # Live migration (kind='slot' artifact): the decode restart state
    # _finish_prefill applies to the promoted slot — generated /
    # outputs / steps, with every already-streamed token pre-emitted.
    restore: Optional[Dict[str, Any]] = None


class _InflightStep:
    """One dispatched-but-not-yet-consumed decode step.

    The dispatch side fills every field except `host`/`error`/
    `t_fetched` and hands the handle to the pipeline fetch thread,
    which ONLY calls device_get on `arrays` (never touches engine
    state) and signals `done`.  The consume side — always the
    scheduler thread — reads `host` and runs all commits.  `rids`
    snapshots each occupied slot's request id at dispatch time so a
    commit after an intervening evict/abort can be skipped instead of
    landing on a recycled slot."""

    __slots__ = ('mode', 'arrays', 'host', 'occupied', 'rids',
                 'read_bytes', 'compiled', 'decode_key', 'spec_n_prop',
                 'spec_proposed', 'mix', 't_enter', 't_dispatched',
                 't_fetched', 'error', 'done')

    def __init__(self, mode: str, arrays: Tuple[Any, ...],
                 occupied: List[int], rids: List[int],
                 read_bytes: float, compiled: bool,
                 decode_key: Any, t_enter: float, t_dispatched: float,
                 spec_n_prop: Any = None, spec_proposed: int = 0,
                 mix: Optional[List[Tuple[Any, int]]] = None):
        self.mode = mode                  # 'plain' | 'mixed' | 'spec'
        self.arrays = arrays              # device futures to fetch
        self.host: Optional[Tuple[Any, ...]] = None
        self.occupied = occupied
        self.rids = rids
        self.read_bytes = read_bytes
        self.compiled = compiled
        self.decode_key = decode_key
        self.spec_n_prop = spec_n_prop    # np [B] int32 (spec mode)
        self.spec_proposed = spec_proposed
        # Mixed-batch prefill: (pending, chunk length) per pending
        # whose chunk rode this step; advanced at CONSUME time.
        self.mix = mix or []
        self.t_enter = t_enter
        self.t_dispatched = t_dispatched
        self.t_fetched: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


# Wake token for the pipeline fetch thread's blocking queue.get() —
# close() enqueues it so shutdown never waits out a poll interval.
_PIPE_STOP = object()


class _ServingMetrics:
    """Get-or-create handles for every serving metric.

    All engines in a process share the same series (the registry
    get-or-creates by name), so constructing several engines — the
    test-suite norm — is cheap and safe.  Metric names follow the
    repo-wide contract ``skytpu_<subsystem>_<what>_<unit-suffix>``
    (guarded by a tier-1 test).  Every update is host-side bookkeeping
    already in hand: nothing here reads a device array.
    """

    def __init__(self, registry: metrics_lib.Registry):
        r = registry
        # Request lifecycle counters.
        self.submitted = r.counter(
            'skytpu_requests_submitted_total',
            'Requests accepted by submit()/generate().')
        self.finished = r.counter(
            'skytpu_requests_finished_total',
            'Requests that completed normally (EOS or budget).')
        self.cancelled = r.counter(
            'skytpu_requests_cancelled_total',
            'Requests cancelled before occupying a decode slot (or '
            'racing completion).')
        self.evicted = r.counter(
            'skytpu_requests_evicted_total',
            'Requests evicted from a decode slot or mid-prefill after '
            'cancel().')
        self.aborted = r.counter(
            'skytpu_requests_aborted_total',
            'In-flight requests dropped by a fatal decode abort().')
        self.deadline_expired = r.counter(
            'skytpu_request_deadline_expired_total',
            'Requests that missed their deadline: expired in the queue '
            'before prefill, or timed out in wait().')
        self.backpressure = r.counter(
            'skytpu_admission_backpressure_total',
            'Admission attempts deferred because the page pool could '
            'not cover the request (retried next tick).')
        # Token counters.
        self.prompt_tokens = r.counter(
            'skytpu_prompt_tokens_total',
            'Prompt tokens admitted for prefill.')
        self.output_tokens = r.counter(
            'skytpu_output_tokens_total',
            'Tokens sampled by decode steps.')
        # Per-step scheduler state.
        self.steps = r.counter(
            'skytpu_decode_steps_total', 'Decode scheduler steps run.')
        self.slot_steps = r.counter(
            'skytpu_decode_slot_steps_total',
            'Sum over decode steps of occupied slots (mean batch '
            'occupancy = slot_steps / (steps * n_slots)).')
        self.decode_kernel_steps = r.counter(
            'skytpu_decode_kernel_steps_total',
            'Decode/verify device steps by paged-attention '
            "implementation: path='fused' walks the block table "
            "in-kernel (ops/paged_attention), path='xla' is the "
            'gather_pages + grouped-einsum path (also counted by '
            'contiguous-cache engines).',
            labelnames=('path',))
        self.live_slots = r.gauge(
            'skytpu_decode_live_slots',
            'Occupied decode slots at the last step.')
        self.occupancy = r.gauge(
            'skytpu_decode_batch_occupancy_ratio',
            'Occupied / total decode slots at the last step.')
        self.queue_depth = r.gauge(
            'skytpu_decode_queue_depth',
            'Requests waiting in the admission queue (backpressure '
            'signal).')
        self.inflight = r.gauge(
            'skytpu_requests_in_flight',
            'Requests queued, prefilling, or decoding right now.')
        self.read_bytes = r.histogram(
            'skytpu_decode_cache_read_bytes',
            'Estimated HBM bytes one decode step reads from the KV '
            'cache (host-side estimate; see decode_cache_read_bytes).',
            buckets=metrics_lib.DEFAULT_BYTE_BUCKETS)
        # Chunked-prefill / mixed-batch series.
        self.prefill_read_bytes = r.histogram(
            'skytpu_prefill_cache_read_bytes',
            'Estimated HBM bytes one chunked-prefill forward reads '
            'from the prefill cache, including the XLA sliced-copy '
            'epilogue the fused ragged-prefill kernel removes '
            '(host-side estimate; see prefill_cache_read_bytes).',
            buckets=metrics_lib.DEFAULT_BYTE_BUCKETS)
        self.prefill_kernel_steps = r.counter(
            'skytpu_prefill_kernel_steps_total',
            'Chunked-prefill forwards by attention implementation: '
            "path='fused' streams the cache prefix page-by-page "
            "in-kernel (ops/ragged_prefill), path='xla' is the "
            'sliced-prefix + grouped-einsum path.',
            labelnames=('path',))
        self.prefill_mix_tokens = r.counter(
            'skytpu_prefill_mix_tokens_total',
            'Prompt tokens admitted into mixed prefill/decode steps '
            '(--prefill-mix-budget > 0): chunk tokens that rode a '
            'decode step instead of a dedicated prefill tick.')
        self.prefill_mixed_steps = r.counter(
            'skytpu_prefill_mixed_steps_total',
            'Decode steps that carried at least one prefill-chunk '
            'token (mixed-batch stepping).')
        # Paged-pool counters/gauges.
        self.free_pages = r.gauge(
            'skytpu_kv_free_pages',
            'KV pages allocatable right now (fresh + reclaimable); 0 '
            'on contiguous-cache engines.')
        self.cannibalized = r.counter(
            'skytpu_kv_pages_cannibalized_total',
            'Reclaimable prefix pages cannibalised by the allocator '
            '(their cached prefix became unmatchable).')
        self.prefix_hits = r.counter(
            'skytpu_prefix_cache_page_hits_total',
            'Prompt pages served from the shared prefix cache (no '
            're-prefill).')
        self.prefix_misses = r.counter(
            'skytpu_prefix_cache_page_misses_total',
            'Prompt pages that had to be freshly allocated/prefilled.')
        # Per-request latency histograms (derived from RequestTrace).
        self.queue_seconds = r.histogram(
            'skytpu_request_queue_seconds',
            'Submit -> admission wait per finished request.')
        self.ttft_seconds = r.histogram(
            'skytpu_request_ttft_seconds',
            'Submit -> first sampled token per finished request.')
        self.tpot_seconds = r.histogram(
            'skytpu_request_tpot_seconds',
            'Mean seconds per output token after the first, per '
            'finished request.')
        # Runtime telemetry: compile/retrace accounting, host-step
        # wall breakdown, memory watermarks.
        self.jit_compiles = r.counter(
            'skytpu_jit_compiles_total',
            'Jitted-path compilations: first call for a new static-'
            'argument/shape key (later increments = retraces).',
            labelnames=('fn',))
        self.jit_compile_seconds = r.histogram(
            'skytpu_jit_compile_seconds',
            'Host wall seconds inside a compiling jitted call '
            '(trace + lower + compile + the first execution).',
            labelnames=('fn',))
        self.dispatch_seconds = r.histogram(
            'skytpu_step_dispatch_seconds',
            'Host wall seconds to enqueue one cache-hit decode step '
            '(async dispatch; the device_get wait is separate).')
        self.device_wait_seconds = r.histogram(
            'skytpu_step_device_wait_seconds',
            'Host wall seconds the scheduler thread spent blocked on '
            'the step\'s sampled tokens (sync: the device_get wall; '
            'async: the pipeline-join wait after host work overlapped).')
        self.host_overlap_seconds = r.histogram(
            'skytpu_step_host_overlap_seconds',
            'Host scheduling/commit wall seconds hidden behind an '
            'in-flight device step by the async pipeline (0 series on '
            'a synchronous engine).')
        self.pipeline_depth = r.gauge(
            'skytpu_pipeline_depth',
            'Decode steps dispatched but not yet consumed (0 = idle '
            'or synchronous loop; the async pipeline is depth-1 '
            'double buffering).')
        self.mesh_devices = r.gauge(
            'skytpu_mesh_devices',
            'Devices in the engine mesh (1 = unsharded single-chip '
            'replica).')
        self.decode_collective_seconds = r.histogram(
            'skytpu_decode_collective_seconds',
            'Host wall seconds blocked on a sharded (mesh devices > '
            '1) decode step\'s results — an upper bound on the '
            'step\'s collective + compute time; 0 series on '
            'single-device engines.')
        self.pages_used_peak = r.gauge(
            'skytpu_kv_pages_used_peak',
            'High-watermark of KV pages in use since engine start '
            '(0 on contiguous-cache engines).')
        # Step-ledger roofline surface (observability/ledger.py): the
        # last committed step's achieved MFU and the analytic forward
        # FLOPs/token at its live context — 0 with the ledger off.
        self.step_mfu = r.gauge(
            'skytpu_step_mfu',
            'Achieved model-FLOPs utilization of the last committed '
            'step (analytic 2*active-params + attention model over '
            'the chip generation\'s bf16 peak; 0 with the step '
            'ledger disabled).')
        self.model_flops_per_token = r.gauge(
            'skytpu_model_flops_per_token',
            'Analytic forward FLOPs per token at the last step\'s '
            'live context (models.flops_per_token_parts; 0 with the '
            'step ledger disabled).')
        self.device_memory_peak = r.gauge(
            'skytpu_device_memory_peak_bytes',
            'Device-allocator peak bytes in use, from '
            'device.memory_stats(); 0 where the backend reports none '
            '(e.g. CPU).')
        # SLO accounting: targets come from SKYTPU_SLO_TTFT_S /
        # SKYTPU_SLO_TPOT_S (seconds; unset or <= 0 disables that SLO).
        self.slo_requests = r.counter(
            'skytpu_slo_requests_total',
            'Finished requests judged against the configured TTFT/'
            'TPOT SLO targets.', labelnames=('slo', 'result'))
        self.slo_ttft_s = _slo_target_from_env('SKYTPU_SLO_TTFT_S')
        self.slo_tpot_s = _slo_target_from_env('SKYTPU_SLO_TPOT_S')

    def observe_finished(self, trace: Optional[tracing_lib.RequestTrace]
                         ) -> None:
        """Record the latency histograms a finished trace derives,
        plus SLO verdicts when targets are configured."""
        if trace is None:
            return
        qs = trace.queue_seconds()
        if qs is not None:
            self.queue_seconds.observe(qs)
        ttft = trace.ttft_seconds()
        if ttft is not None:
            self.ttft_seconds.observe(ttft)
        tpot = trace.tpot_seconds()
        if tpot is not None:
            self.tpot_seconds.observe(tpot)
        if self.slo_ttft_s and ttft is not None:
            self.slo_requests.labels(
                slo='ttft',
                result='good' if ttft <= self.slo_ttft_s
                else 'violated').inc()
        if self.slo_tpot_s and tpot is not None:
            self.slo_requests.labels(
                slo='tpot',
                result='good' if tpot <= self.slo_tpot_s
                else 'violated').inc()


def _handoff_metrics(registry: metrics_lib.Registry) -> Dict[str, Any]:
    """Get-or-create handles for the disaggregated-serving series.
    Registered only on engines with role != 'both' — a plain
    replica's /metrics scrape must not advertise them (the exact-set
    scrape test enforces this)."""
    r = registry
    return {
        'export_seconds': r.histogram(
            'skytpu_handoff_export_seconds',
            'Prefill-role: seconds to turn one finished prefill into '
            'the wire artifact (seed-token sample + device fetch + '
            'encode + slot teardown).'),
        'admit_seconds': r.histogram(
            'skytpu_handoff_admit_seconds',
            'Decode-role: seconds from artifact acceptance to live '
            'decode slot (queue wait + dedupe + cache rebuild + '
            'insert).'),
        'bytes': r.histogram(
            'skytpu_handoff_bytes',
            'Serialized handoff artifact size: form=wire (as '
            'shipped, possibly zlib-compressed) vs form=raw '
            '(uncompressed tensor payload).',
            labelnames=('form',),
            buckets=metrics_lib.DEFAULT_BYTE_BUCKETS),
        'handoffs': r.counter(
            'skytpu_handoff_requests_total',
            "Handoff artifacts by side: side='export' = this prefill "
            "replica serialized one, side='admit' = this decode "
            'replica admitted one into a slot.',
            labelnames=('side',)),
        'pages': r.counter(
            'skytpu_handoff_pages_total',
            'Prompt pages of admitted handoffs: shipped (content '
            'arrived over the wire) vs deduped (already held locally '
            'via the chain-hash prefix map — admitted by page id, '
            'not rewritten).', labelnames=('kind',)),
    }


def _fleet_cache_metrics(registry: metrics_lib.Registry
                         ) -> Dict[str, Any]:
    """Get-or-create handles for the host-RAM / fleet prefix-cache
    series.  Registered only on engines constructed with a host cache
    (host_cache_bytes > 0) — a cache-less replica's scrape must not
    advertise them."""
    r = registry
    return {
        'hits': r.counter(
            'skytpu_fleet_cache_hits_total',
            'Host-tier page lookups that found a spilled copy '
            '(local rehydrate or /kv_prefix serve).'),
        'misses': r.counter(
            'skytpu_fleet_cache_misses_total',
            'Host-tier page lookups that missed.'),
        'spilled_pages': r.counter(
            'skytpu_fleet_cache_spilled_pages_total',
            'Device pages copied to the host-RAM tier just before '
            'their device copy was cannibalised.'),
        'spilled_bytes': r.counter(
            'skytpu_fleet_cache_spilled_bytes_total',
            'Bytes copied device -> host by spills.'),
        'evicted_pages': r.counter(
            'skytpu_fleet_cache_evicted_pages_total',
            'Host-tier pages dropped by its LRU byte budget.'),
        'rehydrated_pages': r.counter(
            'skytpu_fleet_cache_rehydrated_pages_total',
            'Host-tier pages uploaded back into the device pool on a '
            'prefix hit (each one a page of prefill NOT re-run).'),
        'saved_tokens': r.counter(
            'skytpu_fleet_cache_reprefill_tokens_saved_total',
            'Prompt tokens whose prefill was skipped because their '
            'page rehydrated from the host tier.'),
        'stored_bytes': r.gauge(
            'skytpu_fleet_cache_stored_bytes',
            'Bytes currently resident in the host-RAM tier.'),
        'stored_pages': r.gauge(
            'skytpu_fleet_cache_stored_pages',
            'Pages currently resident in the host-RAM tier.'),
    }


def _migration_metrics(registry: metrics_lib.Registry
                       ) -> Dict[str, Any]:
    """Get-or-create handles for the live slot-migration series.
    Registered lazily on first migration activity (any role can drain
    or receive — there is no construction-time migration flag, and an
    idle replica's scrape must not advertise them)."""
    r = registry
    return {
        'migrations': r.counter(
            'skytpu_migration_requests_total',
            "Migrated in-flight slots by side: side='out' = this "
            "replica checkpointed one at drain, side='in' = this "
            'replica resumed one mid-generation.',
            labelnames=('side',)),
        'export_seconds': r.histogram(
            'skytpu_migration_export_seconds',
            'Seconds to checkpoint one live slot into the wire '
            'artifact (pool gather + device fetch + encode + slot '
            'teardown).'),
        'admit_seconds': r.histogram(
            'skytpu_migration_admit_seconds',
            'Seconds from migrated-artifact acceptance to resumed '
            'decode slot.'),
        'bytes': r.histogram(
            'skytpu_migration_bytes',
            'Migrated slot-checkpoint size: form=wire (as shipped, '
            'possibly zlib) vs form=raw (uncompressed tensor bytes).',
            labelnames=('form',),
            buckets=metrics_lib.DEFAULT_BYTE_BUCKETS),
    }


def _publish_device_memory_peak(met: _ServingMetrics) -> None:
    """Set skytpu_device_memory_peak_bytes from the first local
    device's allocator stats.  Scrape-time only — memory_stats() is a
    runtime call, never part of the per-step hot path.  Backends
    without the surface (CPU) leave the gauge at 0."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # pylint: disable=broad-except
        stats = None  # backend-dependent surface; absence is normal
    if stats:
        peak = stats.get('peak_bytes_in_use') or 0
        if peak:
            met.device_memory_peak.set(float(peak))


def _slo_target_from_env(name: str) -> float:
    """SLO target in seconds from the environment; 0.0 = disabled
    (unset, unparseable, or non-positive)."""
    try:
        v = float(os.environ.get(name, '') or 0.0)
    except ValueError:
        return 0.0
    return v if v > 0 else 0.0


def _trace_store_from_env() -> tracing_lib.TraceStore:
    """Engine trace ring, env-tunable: SKYTPU_TRACE_RING caps the
    completed-trace ring, SKYTPU_TRACE_JSONL mirrors transitions to a
    JSONL event sink."""
    try:
        capacity = int(os.environ.get('SKYTPU_TRACE_RING', '') or 256)
    except ValueError:
        capacity = 256
    return tracing_lib.TraceStore(
        capacity=capacity,
        jsonl_path=os.environ.get('SKYTPU_TRACE_JSONL') or None)


def _step_ledger_from_env(config: Any, model_name: str,
                          n_chips: int) -> ledger_lib.StepLedger:
    """Step ledger wired to this engine's model + chips: FLOP
    constants from the analytic per-family estimator, peak/bandwidth
    from the accelerator registry (CPU dev backends normalize to v6e,
    same convention as bench.py's _V6E_TFLOPS fallback, so roofline
    verdicts stay comparable across machines).  SKYTPU_STEP_LEDGER=0
    disables (near-free: record() early-returns per step);
    SKYTPU_STEP_LEDGER_CAP sizes the ring."""
    from skypilot_tpu.utils import accelerator_registry as accel_lib
    enabled = os.environ.get('SKYTPU_STEP_LEDGER', '1') != '0'
    try:
        cap = int(os.environ.get('SKYTPU_STEP_LEDGER_CAP', '') or 512)
    except ValueError:
        cap = 512
    base, attn = models_lib.flops_per_token_parts(config)
    device_kind = jax.devices()[0].device_kind
    gen = accel_lib.generation_for_device_kind(device_kind)
    if gen is None:
        gen = accel_lib.TPU_GENERATIONS['v6e']
    return ledger_lib.StepLedger(
        capacity=cap, enabled=enabled,
        flops_per_token_base=base, attn_flops_per_ctx_token=attn,
        peak_flops_per_sec=gen.bf16_tflops_per_chip * 1e12 * n_chips,
        hbm_bytes_per_sec=gen.hbm_gbps_per_chip * 1e9 * n_chips,
        model=model_name, device_kind=device_kind, n_chips=n_chips)


class ContinuousBatchingEngine:
    """Slot-based continuous batching over the KV-cache model.

    The serving-throughput design the reference gets from vLLM
    (`llm/qwen/serve-110b.yaml`, README.md:54) rebuilt TPU-first:

      - a fixed [n_slots, max_seq_len] KV cache lives across requests;
        every decode step advances ALL occupied slots at once (one
        jitted step, static shapes — no per-request batch formation);
      - new prompts are admitted into free slots BETWEEN decode steps:
        a batch-1 jitted prefill computes the prompt's KV, then a
        jitted insert writes it into the slot's cache row (prefill
        interleaving — decode of live requests is never blocked for
        the whole prefill of a newcomer at the batch level);
      - per-row cache cursors: each slot writes its next token's K/V at
        its own depth (models/llama.py run_cached_attention slot mode —
        the write position is the row's highest revealed kv_mask slot);
      - slots are evicted on EOS / budget and immediately reusable;
      - per-slot temperature, top_k and top_p ride the jit as [B]
        vectors: greedy, top-k and top-p requests interleave in ONE
        decode step with no admission constraint.  The only sampling
        compile keys are coarse capability flags — the power-of-two
        `max_k` bucket and `use_top_p` — so the compile cache is
        bounded by log2(vocab) x 2, not by distinct (k, p) pairs.

    Thread model: `submit()`/`cancel()` are thread-safe; `step()` must
    be driven by ONE thread (the server runs it in a dedicated decode
    loop).
    """

    def __init__(self, model: str = 'llama-tiny',
                 mesh=None,
                 params: Any = None,
                 checkpoint_dir: Optional[str] = None,
                 n_slots: int = 4,
                 max_seq_len: Optional[int] = None,
                 model_overrides: Optional[Dict[str, Any]] = None,
                 param_dtype: Any = jnp.bfloat16,
                 prefill_bucket: int = 64,
                 prefill_chunk: int = 0,
                 kv_read_bucket: int = 512,
                 quantize: Optional[str] = None,
                 kv_cache_dtype: str = 'auto',
                 page_size: int = 0,
                 max_pages: int = 0,
                 seed: int = 0,
                 registry: Optional[metrics_lib.Registry] = None,
                 draft_model: Optional[str] = None,
                 draft_checkpoint_dir: Optional[str] = None,
                 draft_overrides: Optional[Dict[str, Any]] = None,
                 spec_k: int = 0,
                 async_pipeline: bool = True,
                 decode_kernel: str = 'auto',
                 prefill_kernel: str = 'auto',
                 prefill_mix_budget: int = 0,
                 role: str = 'both',
                 host_cache_bytes: int = 0,
                 step_ledger: Optional[ledger_lib.StepLedger] = None
                 ) -> None:
        import collections

        if draft_model is not None and spec_k <= 0:
            raise ValueError('draft_model requires spec_k > 0')
        if host_cache_bytes < 0:
            raise ValueError(
                f'host_cache_bytes must be >= 0, got {host_cache_bytes}')
        if host_cache_bytes > 0 and not page_size:
            raise ValueError(
                'host_cache_bytes requires a paged KV cache '
                '(page_size > 0): the host tier stores pool pages '
                'keyed by the chain-hash prefix map')
        if role not in ('both', 'prefill', 'decode'):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', "
                f'got {role!r}')
        if decode_kernel not in ('auto', 'fused', 'xla'):
            raise ValueError(
                f"decode_kernel must be 'auto', 'fused' or 'xla', "
                f'got {decode_kernel!r}')
        if prefill_kernel not in ('auto', 'fused', 'xla'):
            raise ValueError(
                f"prefill_kernel must be 'auto', 'fused' or 'xla', "
                f'got {prefill_kernel!r}')
        prefill_mix_budget = int(prefill_mix_budget)
        if prefill_mix_budget < 0:
            raise ValueError(
                f'prefill_mix_budget must be >= 0, '
                f'got {prefill_mix_budget}')
        if role == 'prefill' and prefill_mix_budget > 0:
            raise ValueError(
                'role=prefill tears every slot down at prefill end, '
                'so there are no decode steps for mixed-batch chunks '
                'to ride; prefill_mix_budget requires role=both or '
                'role=decode')
        # Model build, param load/sharding, and the [n_slots, ...]
        # cache scaffolding are identical to the request-level engine.
        self._eng = InferenceEngine(
            model=model, mesh=mesh, params=params,
            checkpoint_dir=checkpoint_dir, max_batch_size=n_slots,
            max_seq_len=max_seq_len, model_overrides=model_overrides,
            param_dtype=param_dtype, prefill_bucket=prefill_bucket,
            quantize=quantize, kv_cache_dtype=kv_cache_dtype,
            page_size=page_size, max_pages=max_pages,
            seed=seed, registry=registry)
        self.model = self._eng.model
        self._model_name = str(model)
        self.config = self._eng.config
        self.quantize = self._eng.quantize
        self.kv_cache_dtype = self._eng.kv_cache_dtype
        self.loaded_real_weights = self._eng.loaded_real_weights
        self.mesh = mesh
        self.n_slots = n_slots
        self.max_seq_len = self._eng.max_seq_len
        self.page_size = self._eng.page_size
        self.n_pages = self._eng.n_pages

        # Attention-kernel implementations (--decode-kernel /
        # --prefill-kernel) — the full resolution/validation table
        # lives in resolve_kernels (startup ValueError, never a Pallas
        # partitioning crash mid-serve).
        self.pool_kvh = self._eng.pool_kvh
        tensor = max(mesh.shape.get('tensor', 1), 1) \
            if mesh is not None else 1
        kernels = resolve_kernels(
            decode_kernel, prefill_kernel,
            on_tpu=jax.default_backend() == 'tpu',
            page_size=self.page_size, tensor=tensor,
            pool_kvh=self.pool_kvh)
        self.decode_kernel, self.decode_kernel_interpret = \
            kernels['decode']
        self.prefill_kernel, self.prefill_kernel_interpret = \
            kernels['prefill']
        # Mixed-batch stepping (--prefill-mix-budget): each decode
        # step admits up to this many prefill-chunk tokens into the
        # same jitted graph (0 = dedicated prefill ticks only).
        self.prefill_mix_budget = prefill_mix_budget
        # Static query length of the mixed step: the budget, but at
        # least 2 so the s>1 verify-window write path is exercised
        # even at budget=1 (s==1 is the one-token decode layout).
        self._mix_s = max(2, prefill_mix_budget) \
            if prefill_mix_budget else 0

        # Batch-1 prefill cache template.
        rng = jax.random.PRNGKey(seed)
        abstract1 = jax.eval_shape(
            lambda: self.model.init(rng, jnp.zeros((1, 1), jnp.int32)))
        self._abstract_cache1 = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            sharding_lib.unbox(abstract1['cache']))
        if mesh is not None:
            self._cache1_shardings = jax.tree.map(
                functools.partial(_cache_sharding, mesh),
                self._abstract_cache1)
        else:
            self._cache1_shardings = None

        def _forward(p, cache, tokens, positions, kv_mask):
            p = maybe_dequantize_params(p, self.config.param_dtype)
            logits, mutated = self.model.apply(
                {'params': p, 'cache': cache}, tokens, positions,
                kv_mask, mutable=['cache'])
            return logits, mutated['cache']

        def _prefill_fwd(p, cache, tokens, positions, kv_mask,
                         kv_bucket: int):
            """Chunked-prefill forward with the cache READS capped at
            `kv_bucket` (0 = uncapped).  The bucket is a trace-time
            value (models/llama.py thread-local), so it MUST be a
            static compile key here — a traced-through value would
            silently pin every later chunk to the first chunk's
            bucket via the jit cache."""
            from skypilot_tpu.models import llama as llama_lib
            with llama_lib.kv_read_bucket(
                    kv_bucket if kv_bucket > 0 else None), \
                    llama_lib.prefill_kernel(self.prefill_kernel):
                return _forward(p, cache, tokens, positions, kv_mask)

        self._prefill1 = jax.jit(_prefill_fwd,
                                 static_argnames=('kv_bucket',),
                                 donate_argnums=(1,))

        self._insert = jax.jit(make_insert_fn(), donate_argnums=(0, 1, 2))

        self._alloc = None
        if self.page_size:
            from skypilot_tpu.infer import paging as paging_lib
            ps = self.page_size
            pps = self.max_seq_len // ps
            self._pages_per_slot = pps
            self._alloc = paging_lib.PageAllocator(self.n_pages, ps)

            self._insert_paged = jax.jit(make_paged_insert_fn(ps, pps),
                                         donate_argnums=(0, 1, 2))

            def _hydrate(cache1, cache, table_row, shared_pages,
                         shared_len):
                """Prefix hit: gather the slot's `shared_pages` leading
                pages from the pool into the contiguous batch-1
                prefill cache and advance its cursor to `shared_len`,
                so the suffix chunks attend to the shared prefix
                without re-prefilling it.  Positions past the prefix
                gather the null page — garbage, but every such column
                is either overwritten by a suffix chunk before its row
                reads it (causal) or masked off (kv_mask/causal)."""
                flat = {
                    _path_names(p_): leaf for p_, leaf in
                    jax.tree_util.tree_flatten_with_path(cache)[0]}
                phys = jnp.where(jnp.arange(pps) < shared_pages,
                                 table_row, 0)

                def _gather(path, small):
                    names = _path_names(path)
                    pool_name = _POOL_OF_CONTIG.get(names[-1])
                    if pool_name is not None:
                        pool = flat[names[:-1] + (pool_name,)]
                        if small.ndim == 4:     # [1, kvh, S, d]
                            g = jnp.take(pool, phys, axis=0)
                            g = jnp.transpose(g, (1, 0, 2, 3))
                            return g.reshape(small.shape[1:])[None]
                        L = pool.shape[0]       # scanned
                        g = jnp.take(pool, phys, axis=1)
                        g = jnp.transpose(g, (0, 2, 1, 3, 4))
                        return g.reshape(
                            (L,) + small.shape[2:])[:, None]
                    if names[-1] == 'cache_index':
                        return jnp.full(small.shape, shared_len,
                                        small.dtype)
                    return small

                return jax.tree_util.tree_map_with_path(_gather,
                                                        cache1)

            self._hydrate1 = jax.jit(_hydrate, donate_argnums=(0,))

            self._clear_table = jax.jit(make_clear_table_fn(),
                                        donate_argnums=(0,))

            self._set_table = jax.jit(make_set_table_fn(),
                                      donate_argnums=(0,))

        def _decode_step(p, cache, last, kv_mask, rope_pos, cursors,
                         seeds, gens, active, temps, top_ks, top_ps,
                         max_k: int, use_top_p: bool,
                         top_p_in_topk: bool, kv_bucket: int):
            """Fused: sample every slot's next token from `last`,
            reveal each ACTIVE slot's write position, one-token
            forward for all slots.  Per-row keys fold (request seed,
            generated index) so a seeded request's continuation is
            reproducible regardless of batch composition or admission
            time.  top_ks/top_ps ride the jit as [B] vectors — one
            compile serves heterogeneous sampling configs; the only
            static keys are the coarse capability flags (`max_k`
            power-of-two bucket, `use_top_p`) and `kv_bucket`, which
            caps the decode attention's cache READS to the live prefix
            — one compile per bucket, big HBM savings while contexts
            are short."""
            from skypilot_tpu.models import llama as llama_lib
            keys = jax.vmap(
                lambda sd, g: jax.random.fold_in(
                    jax.random.PRNGKey(sd), g))(seeds, gens)
            tok = sample_logits_rows(last, keys, temps, top_ks, top_ps,
                                     max_k=max_k, use_top_p=use_top_p,
                                     top_p_in_topk=top_p_in_topk)
            brange = jnp.arange(tok.shape[0])
            reveal = kv_mask[brange, cursors] | active
            kv_mask = kv_mask.at[brange, cursors].set(reveal)
            with llama_lib.kv_read_bucket(kv_bucket), \
                    llama_lib.decode_kernel(self.decode_kernel):
                logits, cache = _forward(p, cache, tok[:, None],
                                         rope_pos[:, None], kv_mask)
            return tok, logits[:, 0], cache, kv_mask

        self._decode = jax.jit(
            _decode_step,
            static_argnames=('max_k', 'use_top_p', 'top_p_in_topk',
                             'kv_bucket'),
            donate_argnums=(1, 3))

        # -- mixed-batch stepping (--prefill-mix-budget) --------------
        # One decode step that ALSO carries a bounded budget of
        # prefill-chunk tokens: decode rows feed their sampled token
        # at query 0 (pad queries after it), prefill rows feed chunk
        # tokens, and the s>1 per-row verify-window machinery
        # (models/llama.py _verify_positions/_verify_mask) gives every
        # row its own write base and causal staircase — long prompts
        # amortize across decode steps instead of stalling them.
        def _reserve_mask_row(kv_mask, mask_row, slot):
            """Mixed admission: reset the slot's kv_mask row (prefix
            hits arrive pre-revealed; everything else hidden)."""
            return jax.lax.dynamic_update_slice(
                kv_mask, mask_row[None], (slot, 0))

        self._reserve_mask_row = jax.jit(_reserve_mask_row,
                                         donate_argnums=(0,))

        def _mixed_step(p, cache, last, kv_mask, tokens, rope_pos,
                        cursors, seeds, gens, active, n_commit,
                        last_pos, update_last, temps, top_ks, top_ps,
                        max_k: int, use_top_p: bool,
                        top_p_in_topk: bool, kv_bucket: int):
            """The plain decode step generalized to s > 1 queries per
            row.  Decode rows (active) sample from `last` exactly like
            _decode_step and feed the token at query 0; prefill rows
            feed `n_commit` chunk tokens from `tokens`.  Every working
            row's query-0 slot is revealed BEFORE the forward (the
            write-base protocol _verify_positions expects — for decode
            rows that is the new token's cursor, for prefill rows the
            chunk's cache cursor), the forward writes all s positions
            at base..base+s-1, and only [cursor, cursor+n_commit) is
            revealed afterwards — pad queries' K/V stays unrevealed
            garbage that the next step overwrites in place, the same
            no-copy rollback the speculative verify uses.  `last` is
            refreshed from each row's `last_pos` query (0 for decode
            rows, take-1 for a prompt-completing prefill row — the
            last true token's logits, bit-identical to what the
            unmixed insert path stages)."""
            from skypilot_tpu.models import llama as llama_lib
            keys = jax.vmap(
                lambda sd, g: jax.random.fold_in(
                    jax.random.PRNGKey(sd), g))(seeds, gens)
            tok = sample_logits_rows(last, keys, temps, top_ks, top_ps,
                                     max_k=max_k, use_top_p=use_top_p,
                                     top_p_in_topk=top_p_in_topk)
            brange = jnp.arange(tok.shape[0])
            has_work = n_commit > 0
            reveal = kv_mask[brange, cursors] | has_work
            kv_mask = kv_mask.at[brange, cursors].set(reveal)
            feed0 = jnp.where(active, tok, tokens[:, 0])
            feed = jnp.concatenate([feed0[:, None], tokens[:, 1:]],
                                   axis=1)
            s = feed.shape[1]
            positions = rope_pos[:, None] + jnp.arange(
                s, dtype=jnp.int32)[None, :]
            with llama_lib.kv_read_bucket(kv_bucket), \
                    llama_lib.decode_kernel(self.decode_kernel):
                logits, cache = _forward(p, cache, feed, positions,
                                         kv_mask)
            slots_idx = jnp.arange(kv_mask.shape[1], dtype=jnp.int32)
            window = (has_work[:, None]
                      & (slots_idx[None, :] >= cursors[:, None])
                      & (slots_idx[None, :]
                         < (cursors + n_commit)[:, None]))
            kv_mask = kv_mask | window
            new_last = logits[brange, last_pos]
            last = jnp.where(update_last[:, None], new_last, last)
            return tok, last, cache, kv_mask

        self._mixed = jax.jit(
            _mixed_step,
            static_argnames=('max_k', 'use_top_p', 'top_p_in_topk',
                             'kv_bucket'),
            donate_argnums=(1, 3))

        def _seed_sample(last_row, seed_, temp, top_k, top_p,
                         max_k: int, use_top_p: bool,
                         top_p_in_topk: bool):
            """First-token sample at prefill end, used by two
            consumers that both need token 1 BEFORE any decode step:
            spec mode (the verify step feeds a PENDING token, so it
            is drawn from the prefill logits immediately) and
            role='prefill' (the seed token streams to the client and
            ships in the handoff artifact).  Same kernel + (seed, 0)
            key fold as the fused decode step's generated=0 draw —
            bit-identical numerics, and TTFT no longer waits for the
            first decode tick."""
            key = jax.random.fold_in(jax.random.PRNGKey(seed_), 0)
            return sample_logits_rows(
                last_row[None], key[None], temp[None], top_k[None],
                top_p[None], max_k=max_k, use_top_p=use_top_p,
                top_p_in_topk=top_p_in_topk)[0]

        self._seed_sample = jax.jit(
            _seed_sample,
            static_argnames=('max_k', 'use_top_p', 'top_p_in_topk'))

        # -- speculative decoding (infer/speculative.py) --------------
        # spec_k > 0 swaps the one-token decode above for a verify
        # step: k proposed tokens + the pending token forward together
        # (s = k+1 multi-token slot attention), the acceptance kernel
        # keeps the longest target-approved prefix, and the commit
        # reveals only that prefix's cache slots — 1..k+1 tokens per
        # target forward, output distribution unchanged.
        self.spec_k = spec_k
        self._draft = None
        self._spec_met = None
        self._spec_steps_n = 0
        self._spec_proposed_n = 0
        self._spec_accepted_n = 0
        self._spec_keys_seen: set = set()
        if spec_k:
            from skypilot_tpu.infer import speculative as spec_lib
            if draft_model is not None:
                self._draft = spec_lib.DraftRunner(
                    draft_model,
                    target_vocab_size=self.config.vocab_size,
                    n_slots=n_slots, max_seq_len=self.max_seq_len,
                    spec_k=spec_k, mesh=mesh,
                    checkpoint_dir=draft_checkpoint_dir,
                    model_overrides=draft_overrides,
                    param_dtype=param_dtype,
                    prefill_bucket=prefill_bucket,
                    kv_cache_dtype=kv_cache_dtype,
                    page_size=page_size, seed=seed)

            # Mixed-batch stepping composes with speculation through
            # the SAME verify graph: a prefill row rides the s = k+1
            # forward with its chunk tokens in the t_pend/drafts lanes
            # (active=False, n_prop=0 — acceptance ignores it),
            # mix_real[i] = chunk length drives its reveal window, and
            # a prompt-completing row's seeding draw happens in-graph
            # (the same key fold and kernel as _seed_sample above, so
            # streams stay bit-identical to the unmixed engine).
            mix_on = prefill_mix_budget > 0

            def _spec_verify(p, cache, kv_mask, t_pend, drafts, rope,
                             cursors, n_prop, seeds, gens, active,
                             temps, top_ks, top_ps, mix_real, mix_seed,
                             max_k: int, use_top_p: bool,
                             top_p_in_topk: bool, kv_bucket: int):
                """Fused verify: reveal each active row's pending slot
                (exactly what the one-token step reveals), forward all
                k+1 positions, run acceptance, and reveal ONLY the
                committed window [cursor, cursor+count).  Rejected
                proposals' K/V stays masked — rollback without a copy;
                the next verify overwrites those slots in place.

                mix_real/mix_seed (mixed-batch prefill; all-zero and
                dead-code-eliminated when the budget is 0): rows with
                mix_real > 0 are prefill rows — their chunk of
                mix_real prompt tokens is revealed wholesale, and rows
                flagged mix_seed get out[:, 0] replaced by the
                first-token seeding draw from the prompt's last true
                logits."""
                from skypilot_tpu.infer import speculative as sl
                from skypilot_tpu.models import llama as llama_lib
                brange = jnp.arange(t_pend.shape[0])
                act_w = (active | (mix_real > 0)) if mix_on else active
                reveal = kv_mask[brange, cursors] | act_w
                kv_mask = kv_mask.at[brange, cursors].set(reveal)
                tokens = jnp.concatenate([t_pend[:, None], drafts],
                                         axis=1)
                positions = rope[:, None] + jnp.arange(
                    drafts.shape[1] + 1, dtype=jnp.int32)[None, :]
                with llama_lib.kv_read_bucket(kv_bucket), \
                        llama_lib.decode_kernel(self.decode_kernel):
                    logits, cache = _forward(p, cache, tokens,
                                             positions, kv_mask)
                out, counts = sl.accept_draft_rows(
                    logits, drafts, n_prop, seeds, gens, temps,
                    top_ks, top_ps, max_k=max_k, use_top_p=use_top_p,
                    top_p_in_topk=top_p_in_topk)
                counts = jnp.where(active, counts, 0)
                counts_w = (jnp.where(mix_real > 0, mix_real, counts)
                            if mix_on else counts)
                slots_idx = jnp.arange(kv_mask.shape[1],
                                       dtype=jnp.int32)
                window = (act_w[:, None]
                          & (slots_idx[None, :] >= cursors[:, None])
                          & (slots_idx[None, :]
                             < (cursors + counts_w)[:, None]))
                kv_mask = kv_mask | window
                if mix_on:
                    keys0 = jax.vmap(
                        lambda sd: jax.random.fold_in(
                            jax.random.PRNGKey(sd), 0))(seeds)
                    seed_logits = logits[
                        brange, jnp.maximum(mix_real - 1, 0)]
                    seed_tok = sample_logits_rows(
                        seed_logits, keys0, temps, top_ks, top_ps,
                        max_k=max_k, use_top_p=use_top_p,
                        top_p_in_topk=top_p_in_topk)
                    out = out.at[:, 0].set(
                        jnp.where(mix_seed, seed_tok, out[:, 0]))
                return out, counts, cache, kv_mask

            self._spec_verify = jax.jit(
                _spec_verify,
                static_argnames=('max_k', 'use_top_p', 'top_p_in_topk',
                                 'kv_bucket'),
                donate_argnums=(1, 2))

        self._cache = self._eng._fresh_cache()
        self._last = jnp.zeros((n_slots, self.config.vocab_size),
                               jnp.float32)
        self._kv_mask = jnp.zeros((n_slots, self.max_seq_len), bool)
        self._slots: List[Optional[_Slot]] = [None] * n_slots
        self._queue = collections.deque()
        self._results: Dict[int, List[int]] = {}
        self._events: Dict[int, threading.Event] = {}
        self._canceled: set = set()
        self._admitting_rid: Optional[int] = None
        self._fatal: Optional[BaseException] = None
        # prefill_chunk > 0: prompts longer than this prefill one
        # chunk per tick (decode of live slots interleaves between
        # chunks).  0 = whole-prompt prefill at admission.
        self.prefill_chunk = prefill_chunk
        self._prefills: List[_PendingPrefill] = []
        # Disaggregated serving (--role): 'prefill' replicas run the
        # prompt's chunked prefill then hand the request to a decode
        # replica as a wire artifact (infer/handoff.py) instead of
        # decoding; 'decode' replicas additionally admit those
        # artifacts mid-stream.  'both' (the default) is the classic
        # single-replica engine and changes nothing.
        self.role = role
        # rid -> serialized artifact parked by _handoff_export for the
        # server thread to take (take_handoff) and relay to a decode
        # replica.
        self._handoffs: Dict[int, bytes] = {}
        # (rid, meta, tensors, t_accept) artifacts accepted by
        # admit_handoff; _schedule_front admits them into free slots
        # AHEAD of the regular queue — their prefill cost was already
        # spent on another replica.
        self._handoff_queue: Any = collections.deque()
        # Decode-read bucket granularity (0 disables the read cap).
        self.kv_read_bucket = kv_read_bucket
        self._submit_lock = threading.Lock()
        self._next_rid = 0
        self._seed0 = seed
        # rid -> per-token queue for stream() readers (SSE serving).
        # Tokens are pushed as they decode; completion/cancel/abort
        # push a sentinel so readers never block forever.
        self._stream_queues: Dict[int, Any] = {}
        # rid -> per-request failure (deadline expiry, recovery abort,
        # contained prefill error).  wait()/stream() raise and clear.
        self._errors: Dict[int, BaseException] = {}
        # rid -> absolute time.monotonic() deadline (requests without
        # one have no entry).  Queue expiry and wait() both key off it.
        self._deadlines: Dict[int, float] = {}
        # EWMA of finished requests' submit->finish seconds; feeds the
        # admission-wait estimate load shedding uses.  Only the
        # scheduler thread writes it.
        self._service_ewma_s: Optional[float] = None

        # -- async decode pipeline (double-buffered stepping) ---------
        # When on, each tick dispatches step N+1 while a fetch thread
        # drains step N's tokens, so host scheduling/commit work hides
        # behind device execution.  Depth is exactly 1: `_inflight`
        # holds the single outstanding handle.  The fetch thread is
        # lazily started on first dispatch and ONLY ever touches the
        # handle it is given — all slot/cache/allocator mutation stays
        # on the scheduler thread.
        self.async_pipeline = bool(async_pipeline)
        self._inflight: Optional[_InflightStep] = None
        self._pipe_queue: Optional[queue_lib.Queue] = None
        self._pipe_thread: Optional[threading.Thread] = None
        self._pipe_stop: Optional[threading.Event] = None
        self._pipe_steps_overlapped = 0
        # Test seam: seconds the fetch thread sleeps before device_get
        # (a deliberately slowed consumer for TPOT-attribution tests).
        self._pipeline_delay_s = 0.0

        # -- telemetry (host-side only; see _publish_step_metrics) ----
        self.registry = (registry if registry is not None
                         else metrics_lib.get_registry())
        self._met = _ServingMetrics(self.registry)
        self._mesh_devices = (mesh.devices.size if mesh is not None
                              else 1)
        self._met.mesh_devices.set(self._mesh_devices)
        if self.spec_k:
            # Spec series registered only on speculating engines: a
            # plain replica's /metrics scrape must not advertise them.
            from skypilot_tpu.infer import speculative as spec_lib
            self._spec_met = spec_lib.spec_metrics(self.registry)
        # Handoff series likewise register only on disaggregated
        # replicas — a --role both scrape must not advertise them.
        self._handoff_met = None
        if role != 'both':
            self._handoff_met = _handoff_metrics(self.registry)
        self.traces = _trace_store_from_env()
        self._cannibalized_seen = 0
        # Compile/retrace accounting: the jitted decode/prefill paths
        # recompile once per distinct static-argument key, so "first
        # sight of a key" is a compile and everything after is a
        # cache-hit dispatch.  Host-side sets — no private JAX APIs.
        self._decode_keys_seen: set = set()
        self._prefill_keys_seen: set = set()
        self._pages_used_peak = 0
        # Precomputed read-traffic constants so the per-step estimate
        # is O(live slots) arithmetic, not a cache-pytree walk:
        # paged — bytes one PAGE contributes across all K/V leaves;
        # contiguous — bytes ONE read position contributes across all
        # B rows and leaves (a decode step reads `bucket` positions).
        if self.page_size:
            self._read_bytes_per_page = self._eng.cache_read_bytes_per_step(
                row_contexts=[1])['grouped_bytes']
            self._read_bytes_per_pos = 0.0
            # XLA-path gather epilogue: bytes ONE page of the shared
            # read window costs PER SLOT (the gathered contiguous copy
            # is written then re-read for every row at the bucketed
            # window, live or not).  Zero on the fused kernel — it
            # streams pool tiles straight into VMEM.
            if self.decode_kernel == 'xla':
                self._epilogue_bytes_per_page = \
                    self._eng.cache_read_bytes_per_step(
                        row_contexts=[1])['epilogue_bytes']
            else:
                self._epilogue_bytes_per_page = 0.0
        else:
            self._read_bytes_per_page = 0.0
            self._epilogue_bytes_per_page = 0.0
            self._read_bytes_per_pos = self._eng.cache_read_bytes_per_step(
                context=1)['grouped_bytes']
        # Prefill read-traffic constants (per read POSITION of the
        # batch-1 prefill cache): grouped = the prefix streamed once;
        # epilogue = the XLA sliced-copy cost, exactly 0 under the
        # fused ragged-prefill kernel — so the per-chunk estimate is
        # two multiplies, not a pytree walk.
        _pr = prefill_cache_read_bytes(
            self._abstract_cache1, self.config.n_heads, context=1,
            prefill_kernel=self.prefill_kernel)
        self._prefill_read_bytes_per_pos = _pr['grouped_bytes']
        self._prefill_epilogue_bytes_per_pos = _pr['epilogue_bytes']
        # -- step-level performance ledger (observability/ledger.py) --
        # Fed at step-COMMIT time in _consume_step (the consume half;
        # the pipeline-discipline rule keeps it off the dispatch
        # half).  The step counter increments whether or not the
        # ledger records, so trace step-index joins survive a
        # disabled ledger.
        self._step_idx = 0
        self.step_ledger = (step_ledger if step_ledger is not None
                            else _step_ledger_from_env(
                                self.config, self._model_name,
                                self._mesh_devices))

        # -- host-RAM spill tier + fleet prefix cache -----------------
        # (infer/fleet_cache.py).  When configured, the allocator's
        # cannibalisation path spills victim pages to host RAM instead
        # of discarding them, and _admit rehydrates them on a later
        # prefix hit — microseconds instead of a re-prefill.  The
        # same tier backs GET /kv_prefix for fleet-peer warm-up.
        self.host_cache_bytes = int(host_cache_bytes)
        self._host_cache = None
        self._fleet_met = None
        if self.host_cache_bytes > 0:
            from skypilot_tpu.infer import fleet_cache as fleet_lib
            self._host_cache = fleet_lib.HostPrefixCache(
                self.host_cache_bytes)
            self._alloc.set_spill_hooks(self._spill_page,
                                        self._host_cache.has)
            self._fleet_met = _fleet_cache_metrics(self.registry)
            # Jitted pool-page writer for rehydration: donates the
            # shared cache and scatters one page's leaves at a traced
            # page id (single compile for any page).
            self._page_write = jax.jit(make_page_write_fn(),
                                       donate_argnums=(0,))
            # Expected per-page leaf shapes/dtypes, keyed like the
            # host tier: pool leaves with the page axis dropped.
            # Validates peer-fetched pages before they can reach the
            # jitted writer.
            self._pool_page_specs: Dict[str, Any] = {}
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    self._cache)[0]:
                names = _path_names(path)
                if names[-1] not in _CONTIG_OF_POOL:
                    continue
                key = '/'.join(str(n) for n in names)
                shape = (leaf.shape[1:] if leaf.ndim == 4
                         else leaf.shape[:1] + leaf.shape[2:])
                self._pool_page_specs[key] = (shape,
                                              np.dtype(leaf.dtype))
        # Last-published fleet-cache counter values (diffed per step
        # by _publish_step_metrics, same pattern as cannibalized).
        self._spilled_seen = 0
        self._spilled_bytes = 0
        self._spilled_bytes_seen = 0
        self._rehydrated_pages = 0
        self._rehydrated_seen = 0
        self._saved_tokens = 0
        self._saved_seen = 0
        self._fleet_hits_seen = 0
        self._fleet_misses_seen = 0
        self._fleet_evicted_seen = 0
        # -- live migration (drain/preemption) ------------------------
        # request_migrate_out() arms the flag from a server thread;
        # the scheduler's next step() checkpoints every occupied slot
        # into a kind='slot' artifact parked in _handoffs for the
        # server to relay to a survivor.  Metrics register lazily on
        # first migration activity.
        self._migrate_requested = False
        self._migration_met = None
        # SKHO v2 zlib tensor section (opt-in; both sides run v2, so
        # no negotiation beyond the version field is needed).
        self._handoff_compress = \
            os.environ.get('SKYTPU_HANDOFF_COMPRESS', '') == '1'

    def cache_read_bytes_per_step(self, context: Optional[int] = None,
                                  row_contexts: Optional[Sequence[int]]
                                  = None) -> Dict[str, float]:
        """Estimated HBM bytes one decode step reads from the shared
        cache — see decode_cache_read_bytes.  On a paged engine with
        no explicit `row_contexts`, the LIVE slots' contexts are used
        (a decode step gathers only allocated pages), falling back to
        the all-slots-at-`context` worst case when idle.  The engine's
        own --decode-kernel choice sets the epilogue term: the XLA
        gather path pays it, the fused kernel reports 0."""
        if self.page_size and row_contexts is None:
            live = [s.pad_len + s.generated + 1
                    for s in self._slots if s is not None]
            row_contexts = live or None
        return self._eng.cache_read_bytes_per_step(
            context, row_contexts, decode_kernel=self.decode_kernel)

    def decode_kernel_info(self) -> Dict[str, Any]:
        """decode_kernel block for /health?verbose=1: the resolved
        paged-attention implementation, the page geometry it runs
        over, and whether the Pallas kernel is in interpreter mode
        (fused off-TPU — tests/benches only, never the 'auto'
        default)."""
        return dict(
            path=self.decode_kernel,
            page_size=self.page_size,
            interpret=self.decode_kernel_interpret,
        )

    def prefill_read_bytes_per_chunk(self, context: int
                                     ) -> Dict[str, float]:
        """Estimated HBM bytes one chunked-prefill forward reads from
        the batch-1 prefill cache at read window `context` — see
        prefill_cache_read_bytes.  The engine's own --prefill-kernel
        choice sets the epilogue term: the XLA sliced-copy path pays
        it, the fused ragged-prefill kernel reports 0."""
        return prefill_cache_read_bytes(
            self._abstract_cache1, self.config.n_heads, context,
            prefill_kernel=self.prefill_kernel)

    def prefill_kernel_info(self) -> Dict[str, Any]:
        """prefill block for /health?verbose=1: the resolved
        chunked-prefill attention implementation, its interpreter
        flag, the mixed-batch token budget, and how many prompts are
        mid-prefill right now."""
        return dict(
            path=self.prefill_kernel,
            page_size=self.page_size,
            interpret=self.prefill_kernel_interpret,
            mix_budget=self.prefill_mix_budget,
            pending=len(self._prefills),
        )

    def sharding_info(self) -> Dict[str, Any]:
        """`sharding` block for /health?verbose=1 — see
        InferenceEngine.sharding_info."""
        return self._eng.sharding_info()

    @property
    def params(self):
        return self._eng.params

    # -- request intake ----------------------------------------------------
    _STREAM_END = None  # queue sentinel: request finished/canceled

    def submit(self, prompt_ids: Sequence[int],
               sampling: Optional[SamplingConfig] = None,
               stream: bool = False,
               deadline_s: Optional[float] = None,
               http_request_id: Optional[str] = None,
               trace_parent: Optional[str] = None) -> int:
        """Enqueue one prompt; returns a request id for wait() (or,
        with stream=True, for stream() — tokens are then ALSO pushed
        to a per-request queue as each decode step lands).

        `deadline_s` is a relative wall-clock budget: the request is
        expired in the queue once it passes (before wasting prefill),
        and wait() without an explicit timeout blocks at most until
        it.

        `http_request_id` / `trace_parent` stamp the external request
        id (and the router's attempt-span id from X-Skytpu-Trace) on
        the trace from birth, so every JSONL event line carries the
        external id and stitched fleet traces can join on it."""
        import queue as queue_mod
        import threading
        cfg = sampling or SamplingConfig()
        if len(prompt_ids) == 0:
            raise ValueError('empty prompt')
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f'deadline_s must be a number: {deadline_s!r}') from e
            if deadline_s <= 0:
                raise ValueError(
                    f'deadline_s must be > 0, got {deadline_s}')
        if cfg.max_new_tokens < 1:
            # step() appends the sampled token before checking the
            # budget, so 0/negative would still emit one token (and a
            # negative value breaks the _admit pad clamp).
            raise ValueError(
                f'max_new_tokens must be >= 1, got {cfg.max_new_tokens}')
        if len(prompt_ids) + cfg.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f'prompt ({len(prompt_ids)}) + max_new_tokens '
                f'({cfg.max_new_tokens}) exceeds max_seq_len '
                f'{self.max_seq_len}.')
        if self.page_size:
            # A request that could never fit the page pool must fail
            # HERE (caller thread, -> 400): admission backpressure
            # retries on the assumption that draining slots will free
            # pages, which never helps when the worst-case footprint
            # exceeds the pool itself.
            pad, need = self._page_need(len(prompt_ids), cfg)
            if need > self._alloc.capacity:
                raise ValueError(
                    f'request needs {need} KV pages (prompt '
                    f'{len(prompt_ids)} tokens padded to {pad} for '
                    f'the prefill bucket, + max_new_tokens '
                    f'{cfg.max_new_tokens}, page_size '
                    f'{self.page_size}) but the pool holds only '
                    f'{self._alloc.capacity}; raise max_pages or '
                    f'lower max_new_tokens.')
        if cfg.seed is not None:
            # Coerce + mask HERE (caller thread): a bad seed must 400
            # the one request, never blow up the shared decode loop.
            try:
                cfg = dataclasses.replace(
                    cfg, seed=int(cfg.seed) & 0x7FFFFFFF)
            except (TypeError, ValueError) as e:
                raise ValueError(f'seed must be an integer: '
                                 f'{cfg.seed!r}') from e
        with self._submit_lock:
            if self._fatal is not None:
                # The replica is dead; fail fast instead of queueing
                # work whose waiter can only time out.
                raise RuntimeError(
                    f'engine aborted: {self._fatal!r}') from self._fatal
            rid = self._next_rid
            self._next_rid += 1
            self._events[rid] = threading.Event()
            if stream:
                self._stream_queues[rid] = queue_mod.Queue()
            deadline = None
            if deadline_s is not None:
                deadline = time.monotonic() + deadline_s
                self._deadlines[rid] = deadline
            self._queue.append((rid, list(prompt_ids), cfg, deadline))
            depth = len(self._queue) + len(self._handoff_queue)
            # Trace begins inside the lock so the decode thread can
            # never admit this rid before its trace exists.
            trace = self.traces.begin(rid,
                                      prompt_tokens=len(prompt_ids),
                                      http_request_id=http_request_id)
            trace.trace_parent = trace_parent
        self._met.submitted.inc()
        self._met.queue_depth.set(depth)
        self._met.inflight.set(self.traces.inflight_count)
        return rid

    def cancel(self, request_id: int) -> None:
        """Drop a request wherever it is (queued, decoding, or done but
        unread) and release its bookkeeping — abandoned requests must
        not leak results/events in a long-running replica."""
        with self._submit_lock:
            before = len(self._queue) + len(self._handoff_queue)
            self._queue = type(self._queue)(
                item for item in self._queue if item[0] != request_id)
            self._handoff_queue = type(self._handoff_queue)(
                item for item in self._handoff_queue
                if item[0] != request_id)
            removed_queued = (len(self._queue)
                              + len(self._handoff_queue)) != before
            depth = len(self._queue) + len(self._handoff_queue)
            self._results.pop(request_id, None)
            self._events.pop(request_id, None)
            self._errors.pop(request_id, None)
            self._deadlines.pop(request_id, None)
            q = self._stream_queues.pop(request_id, None)
            if q is not None:
                q.put(self._STREAM_END)  # unblock a live reader
            in_engine = request_id == self._admitting_rid or any(
                p.rid == request_id for p in self._prefills) or any(
                s is not None and s.request_id == request_id
                for s in self._slots)
            if in_engine:
                # In a slot — or popped from the queue and mid-prefill
                # (the admission window): step() evicts it next tick.
                self._canceled.add(request_id)
        if removed_queued and not in_engine:
            # Never reached a slot: terminal here.  Slot-resident
            # cancels trace-finish as 'evicted' at the next tick.
            if self.traces.finish(request_id, 'cancelled') is not None:
                self._met.cancelled.inc()
            self._met.inflight.set(self.traces.inflight_count)
        self._met.queue_depth.set(depth)

    def wait(self, request_id: int,
             timeout: Optional[float] = None) -> List[int]:
        """Block until `request_id` finishes; returns its token ids.
        On timeout the request is CANCELED (not left orphaned) and
        TimeoutError raised.  Without an explicit `timeout`, a request
        submitted with `deadline_s` blocks at most until its deadline
        (DeadlineExceededError).  Raises the per-request failure when
        the request was aborted/expired by the engine."""
        event = self._events[request_id]
        deadline = self._deadlines.get(request_id)
        from_deadline = timeout is None and deadline is not None
        if from_deadline:
            timeout = max(0.0, deadline - time.monotonic())
        if not event.wait(timeout):
            self.cancel(request_id)
            if from_deadline:
                self._met.deadline_expired.inc()
                raise failures.DeadlineExceededError(
                    f'request {request_id} missed its deadline')
            raise TimeoutError(f'request {request_id} not done')
        with self._submit_lock:
            err = self._errors.pop(request_id, None)
            if err is not None:
                self._events.pop(request_id, None)
                self._deadlines.pop(request_id, None)
                self._results.pop(request_id, None)
                raise err
            if self._fatal is not None and \
                    request_id not in self._results:
                self._events.pop(request_id, None)
                self._deadlines.pop(request_id, None)
                raise RuntimeError(
                    f'decode loop died: {self._fatal!r}') \
                    from self._fatal
            del self._events[request_id]
            self._deadlines.pop(request_id, None)
            return self._results.pop(request_id)

    def abort(self, error: BaseException) -> None:
        """Fatal decode failure: the engine stops serving.  Wake every
        waiter so none blocks its full timeout (wait() raises for
        requests without results), drop the queue (submit() refuses
        new work once `_fatal` is set), and return in-flight pages to
        the allocator so page accounting ends leak-free even on the
        abandon path.  Device state is left as-is — a dead replica's
        buffers are not worth a device round-trip that may itself
        hang."""
        with self._submit_lock:
            self._fatal = error
            self._queue.clear()
            self._handoff_queue.clear()
            self._handoffs.clear()
            events = list(self._events.values())
            queues = list(self._stream_queues.values())
        self._pipeline_abandon()
        self._drop_inflight()
        for e in events:
            e.set()
        for q in queues:
            q.put(self._STREAM_END)  # stream() re-checks _fatal
        dropped = self.traces.abort_all(error=repr(error))
        if dropped:
            self._met.aborted.inc(len(dropped))
        self._met.inflight.set(self.traces.inflight_count)

    def _drop_inflight(self) -> List[int]:
        """Clear every slot and pending prefill, returning their pages
        to the allocator; returns the rids dropped.  Host-side only:
        no device ops (callers either rebuild the device state —
        recover() — or are abandoning it — abort())."""
        victims: List[int] = []
        for i, s in enumerate(self._slots):
            if s is not None:
                victims.append(s.request_id)
                if self.page_size:
                    for page in s.pages:
                        self._alloc.release(page)
                self._slots[i] = None
        for p in self._prefills:
            victims.append(p.rid)
            if self.page_size:
                for page in p.pages:
                    self._alloc.release(page)
        self._prefills = []
        return victims

    def recover(self, error: BaseException) -> None:
        """Transient-failure recovery: keep the engine serving.

        Called by the decode-loop supervisor (the same thread that
        drives step()) after a step exception.  In-flight slots and
        pending prefills are aborted — their waiters fail fast with
        the cause — while QUEUED requests survive: they have no device
        state yet.  Because the jitted step/insert paths donate the
        cache buffers, a mid-step exception leaves them invalid, so
        all device state is rebuilt from zeros and the allocator is
        reset (its prefix registrations describe cache contents that
        no longer exist).  The allocator must verify leak-free after
        the drop; a failure raises PageLeakError, which classifies
        fatal.

        Pipeline fencing: a step still in flight when the fault hit
        (e.g. the fault was drawn at the top of the NEXT tick) is
        abandoned un-consumed — its device outputs descend from the
        same possibly-invalidated donated buffers being rebuilt here,
        and its slots are among the victims below, so dropping it is
        both safe and required."""
        self._pipeline_abandon()
        victims = self._drop_inflight()
        with self._submit_lock:
            # Every canceled rid was in-engine and was just dropped.
            self._canceled.clear()
            self._admitting_rid = None
            queued = len(self._queue)
        if self._alloc is not None:
            leak = self._alloc.leak_report()
            if leak is not None:
                raise failures.PageLeakError(
                    f'allocator not clean after dropping in-flight '
                    f'work: {leak}')
            self._alloc.reset()
        self._cache = self._eng._fresh_cache()
        self._last = jnp.zeros((self.n_slots, self.config.vocab_size),
                               jnp.float32)
        self._kv_mask = jnp.zeros((self.n_slots, self.max_seq_len),
                                  bool)
        if self._draft is not None:
            # The draft's propose/insert paths donate its buffers the
            # same way: rebuild them from zeros alongside the target's.
            self._draft.reset()
        for rid in victims:
            self._fail_request(rid, failures.wrap_abort(rid, error))
        logger.warning(
            f'engine recovered from {error!r}: aborted {len(victims)} '
            f'in-flight request(s), preserved {queued} queued')

    def _fail_request(self, rid: int, error: BaseException,
                      state: str = 'aborted') -> None:
        """Fail ONE request — record its error, wake its waiter and
        stream reader, finish its trace — while the engine keeps
        serving everything else.  `state='cancelled'` is the queued
        deadline-expiry flavor (counted as a deadline expiry, not an
        abort)."""
        with self._submit_lock:
            self._errors[rid] = error
            self._results.pop(rid, None)
            self._deadlines.pop(rid, None)
            event = self._events.get(rid)
            q = self._stream_queues.get(rid)
        if q is not None:
            q.put(self._STREAM_END)
        if event is not None:
            event.set()
        if self.traces.finish(rid, state, error=repr(error)) is not None:
            if state == 'cancelled':
                self._met.deadline_expired.inc()
                self._met.cancelled.inc()
            else:
                self._met.aborted.inc()
        self._met.inflight.set(self.traces.inflight_count)

    def _expire(self, rid: int) -> None:
        """A queued request whose deadline already passed: terminal
        'cancelled' without wasting a prefill on it."""
        self._fail_request(
            rid,
            failures.DeadlineExceededError(
                f'request {rid} expired in queue before admission'),
            state='cancelled')

    def stream(self, request_id: int, timeout: Optional[float] = None):
        """Yield `request_id`'s tokens as they decode (submit() must
        have been called with stream=True).  `timeout` bounds the gap
        BETWEEN tokens, not the whole generation; on a stall the
        request is canceled and TimeoutError raised.  Raises
        RuntimeError if the decode loop died mid-stream."""
        import queue as queue_mod
        with self._submit_lock:
            q = self._stream_queues.get(request_id)
        if q is None:
            raise KeyError(
                f'request {request_id} was not submitted with '
                f'stream=True (or is already finished).')
        while True:
            try:
                tok = q.get(timeout=timeout)
            except queue_mod.Empty:
                self.cancel(request_id)
                raise TimeoutError(
                    f'request {request_id}: no token within '
                    f'{timeout}s') from None
            if tok is self._STREAM_END:
                with self._submit_lock:
                    fatal = self._fatal
                    err = self._errors.pop(request_id, None)
                    self._stream_queues.pop(request_id, None)
                    # wait()-side bookkeeping: a pure-stream consumer
                    # must not leak the event/result entries.
                    self._events.pop(request_id, None)
                    self._results.pop(request_id, None)
                    self._deadlines.pop(request_id, None)
                if err is not None:
                    # Per-request failure beats replica-fatal: it names
                    # THIS request's cause.
                    raise err
                if fatal is not None:
                    raise RuntimeError(
                        f'decode loop died: {fatal!r}') from fatal
                return
            yield tok

    # -- the decode loop ---------------------------------------------------
    def _fresh_cache1(self):
        def _zeros(leaf, sharding=None):
            if sharding is not None:
                return jnp.zeros(leaf.shape, leaf.dtype, device=sharding)
            return jnp.zeros(leaf.shape, leaf.dtype)
        if self._cache1_shardings is None:
            return jax.tree.map(_zeros, self._abstract_cache1)
        return jax.tree.map(_zeros, self._abstract_cache1,
                            self._cache1_shardings)

    # -- host-RAM spill tier + fleet prefix cache ---------------------

    def _spill_page(self, h: int, page: int) -> None:
        """Allocator spill hook: copy device page `page`'s pool
        contents to the host tier under chain hash `h`, right before
        the device copy is cannibalised.  Runs inside alloc() on the
        scheduler thread; `self._cache` is always a valid (possibly
        not-yet-ready) pool there, and device_get blocks until the
        page's bytes exist."""
        leaves: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self._cache)[0]:
            names = _path_names(path)
            if names[-1] not in _CONTIG_OF_POOL:
                continue
            view = leaf[page] if leaf.ndim == 4 else leaf[:, page]
            leaves['/'.join(str(n) for n in names)] = \
                np.asarray(jax.device_get(view))
        self._host_cache.put(h, leaves)
        self._spilled_bytes += sum(int(a.nbytes)
                                   for a in leaves.values())

    def _rehydrate_chain(self, prompt: List[int], shared: List[int],
                         cap: int) -> List[int]:
        """Extend a device-tier prefix hit through the host tier:
        walk the prompt's chain hashes past `shared`, uploading each
        host-resident page into a fresh pool page (and re-registering
        it) until the first page NO tier holds.  Device-registered
        pages past a rehydrated gap resume by reference.  Every
        returned page is retained, mirroring lookup_prefix."""
        from skypilot_tpu.infer import paging as paging_lib
        hashes = paging_lib.chain_hashes(prompt, self.page_size)
        shared = list(shared)
        while len(shared) < cap:
            h = hashes[len(shared)]
            page = self._alloc.take_registered(h)
            if page is None:
                leaves = self._host_cache.get(h)
                if leaves is None:
                    break
                got = self._alloc.alloc(1)
                if got is None:
                    break                 # pool pressure: stop early
                page = got[0]
                updates = {key: jnp.asarray(arr)
                           for key, arr in leaves.items()}
                try:
                    self._cache = self._page_write(
                        self._cache, jnp.int32(page), updates)
                except Exception as e:  # pylint: disable=broad-except
                    # The writer donates the shared cache; a
                    # mid-donation failure is not containable.
                    raise failures.SharedStateError(
                        f'host-tier rehydrate of page {page} failed '
                        f'mid-donation; shared cache state unknown'
                        ) from e
                self._alloc.adopt_prefix(h, page)
                self._rehydrated_pages += 1
                self._saved_tokens += self.page_size
            shared.append(page)
        return shared

    def kv_prefix_blob(self, hashes: Sequence[int]) -> Optional[bytes]:
        """Serve GET /kv_prefix: the longest leading run of `hashes`
        resident in the host tier, in the SKHO kv_prefix framing.
        None when the tier is off or holds none of the chain.
        Thread-safe — HTTP handler threads call it, and only
        host-tier state is touched."""
        from skypilot_tpu.infer import handoff as handoff_lib
        if self._host_cache is None or not hashes:
            return None
        served_h, served_p = self._host_cache.snapshot_run(hashes)
        if not served_h:
            return None
        return handoff_lib.serialize_kv_prefix(
            self._model_name, self.kv_cache_dtype, self.page_size,
            served_h, served_p, compress=self._handoff_compress)

    def ingest_prefix_pages(self, pages: Sequence[Any]) -> int:
        """Store fleet-peer pages [(chain_hash, {leaf: array})...]
        into the LOCAL host tier; the scheduler's rehydration walk
        picks them up at the next admission.  HTTP-handler-thread
        safe.  Pages failing the pool-leaf geometry check are dropped
        — a peer running different sharding or quantization must not
        poison the tier."""
        if self._host_cache is None:
            return 0
        n = 0
        for h, leaves in pages:
            ok = set(leaves) == set(self._pool_page_specs)
            if ok:
                for key, arr in leaves.items():
                    shape, dtype = self._pool_page_specs[key]
                    if tuple(arr.shape) != tuple(shape) \
                            or np.dtype(arr.dtype) != dtype:
                        ok = False
                        break
            if ok and self._host_cache.put(int(h), dict(leaves)):
                n += 1
        return n

    def prefix_resident_run(self, hashes: Sequence[int]) -> int:
        """Leading run of `hashes` already resident in SOME local tier
        (device prefix map or host cache) — the server's fleet fetch
        skips them and asks the peer only for the missing tail.
        Advisory (racy reads from handler threads): a stale answer
        costs one redundant fetch, never correctness."""
        n = 0
        for h in hashes:
            if self._alloc is not None and self._alloc.has_prefix(h):
                n += 1
            elif self._host_cache is not None \
                    and self._host_cache.has(h):
                n += 1
            else:
                break
        return n

    def host_cache_stats(self) -> Optional[Dict[str, int]]:
        """Host-tier stats + cross-tier lifetime counters for
        /health and the dashboard; None when the tier is off.
        Advisory (racy reads from handler threads)."""
        if self._host_cache is None:
            return None
        s = self._host_cache.stats()
        s['spilled_pages_total'] = self._alloc.spilled_total
        s['spilled_bytes_total'] = self._spilled_bytes
        s['rehydrated_pages_total'] = self._rehydrated_pages
        s['reprefill_tokens_saved_total'] = self._saved_tokens
        return s

    def _page_need(self, true_len: int,
                   cfg: SamplingConfig) -> Tuple[int, int]:
        """(pad, pages) one request will hold at admission: the prompt
        padded to its prefill bucket plus the decode budget, in pages.
        Shared-prefix hits reduce what alloc() must find fresh, never
        the total the request holds — submit() checks this against
        pool CAPACITY so a request that could never fit 400s instead
        of spinning in admission backpressure forever."""
        pad = max(self._eng._bucketed(true_len), true_len)
        pad = min(pad, self.max_seq_len - cfg.max_new_tokens)
        pad = max(pad, true_len)
        need = 0
        if self.page_size:
            need = min(-(-(pad + cfg.max_new_tokens) // self.page_size),
                       self._pages_per_slot)
        return pad, need

    def _admit(self, slot_idx: int, rid: int, prompt: List[int],
               cfg: SamplingConfig) -> bool:
        """Reserve slot `slot_idx` for request `rid` and start (or
        finish) its prefill.  Returns False — WITHOUT consuming the
        slot — when the paged allocator cannot cover the request
        (admission backpressure: the caller requeues and retries after
        decode frees pages)."""
        true_len = len(prompt)
        pad, need = self._page_need(true_len, cfg)
        pages: List[int] = []
        table_row = None
        shared_len = 0
        if self.page_size:
            ps = self.page_size
            # Prefix sharing: reuse every already-cached page-aligned
            # prompt page — capped one page short of the prompt's end,
            # because the LAST true token must always prefill (its
            # logits seed decode).
            cap = min((true_len - 1) // ps, need)
            shared = self._alloc.lookup_prefix(prompt, max_pages=cap)
            if self._host_cache is not None and len(shared) < cap:
                # Host-tier extension: pages the device pool
                # cannibalised (or a fleet peer shipped) rehydrate
                # into fresh pool pages, skipping their prefill.
                shared = self._rehydrate_chain(prompt, shared, cap)
            fresh = self._alloc.alloc(need - len(shared))
            if fresh is None:
                for page in shared:
                    self._alloc.release(page)
                self._met.backpressure.inc()
                return False
            self._met.prefix_hits.inc(len(shared))
            self._met.prefix_misses.inc(len(fresh))
            pages = list(shared) + fresh
            shared_len = len(shared) * ps
            table_row = np.zeros((self._pages_per_slot,), np.int32)
            table_row[:len(pages)] = pages
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, :true_len] = prompt
        mask_row = np.zeros((self.max_seq_len,), bool)
        mask_row[:true_len] = True
        if self.prefill_mix_budget > 0:
            return self._admit_mixed(slot_idx, rid, cfg, true_len,
                                     pad, tokens, mask_row, pages,
                                     table_row, shared_len)
        try:
            cache1 = self._fresh_cache1()
            if shared_len > 0:
                cache1 = self._hydrate1(
                    cache1, self._cache, jnp.asarray(table_row),
                    jnp.int32(shared_len // self.page_size),
                    jnp.int32(shared_len))
            pending = _PendingPrefill(
                slot_idx=slot_idx, rid=rid, cfg=cfg, true_len=true_len,
                pad=pad, tokens=tokens, mask_row=mask_row,
                cache1=cache1, done=shared_len, pages=pages,
                table_row=table_row, shared_len=shared_len)
            self.traces.event(rid, 'admitted',
                              shared_prefix_tokens=shared_len)
            self._met.prompt_tokens.inc(true_len)
            if self.prefill_chunk > 0:
                # Reserve the slot; one chunk runs per tick from
                # _schedule_front so live slots keep decoding in
                # between.
                self._prefills.append(pending)
                return True
            while pending.done < pending.pad:
                self._prefill_chunk_step(pending)
        except BaseException:
            # Everything above touches only this request's private
            # state: hand its pages back and let the caller contain
            # the failure to this rid.
            self._release_slot_pages(pages)
            raise
        # Park across the shared-cache insert: if it fails
        # (SharedStateError, not containable), the supervisor's
        # recover() finds the pending here, releases its pages and
        # fails the rid.
        self._prefills.append(pending)
        self._finish_prefill(pending)
        self._prefills.pop()
        return True

    def _admit_mixed(self, slot_idx: int, rid: int,
                     cfg: SamplingConfig, true_len: int, pad: int,
                     tokens: Any, mask_row: Any, pages: List[int],
                     table_row: Any, shared_len: int) -> bool:
        """Mixed-batch admission (prefill_mix_budget > 0): there is no
        batch-1 staging cache and no insert — the prompt's chunks ride
        decode steps (_dispatch_mixed / _dispatch_spec) and write
        straight into the slot's shared-cache row / pool pages.
        Admission only RESERVES the slot: reset its kv_mask row (a
        shared prefix arrives pre-revealed — its pages are in the pool
        and the block-table row points at them, so no hydrate is
        needed either) and, on a paged engine, write its device
        block-table row.  Takes precedence over prefill_chunk, which
        only governs the dedicated-tick staging path."""
        seed = cfg.seed if cfg.seed is not None else (
            hash((self._seed0, rid)) & 0x7FFFFFFF)
        pending = _PendingPrefill(
            slot_idx=slot_idx, rid=rid, cfg=cfg, true_len=true_len,
            pad=pad, tokens=tokens, mask_row=mask_row, cache1=None,
            done=shared_len, pages=pages, table_row=table_row,
            shared_len=shared_len, mixed=True, seed=seed)
        # Park BEFORE the donating device calls: on a mid-donation
        # failure the supervisor's recover() finds the pending here,
        # releases its pages (the allocator must verify leak-free) and
        # fails the rid.
        self._prefills.append(pending)
        mask_init = np.zeros((self.max_seq_len,), bool)
        mask_init[:shared_len] = True
        try:
            if self.page_size:
                self._cache = self._set_table(
                    self._cache, jnp.asarray(table_row),
                    jnp.int32(slot_idx))
            self._kv_mask = self._reserve_mask_row(
                self._kv_mask, jnp.asarray(mask_init),
                jnp.int32(slot_idx))
        except Exception as e:  # pylint: disable=broad-except
            # Both calls donate shared device buffers; a mid-donation
            # failure is not containable to this rid.
            raise failures.SharedStateError(
                f'mixed-prefill reservation for request {rid} failed '
                f'mid-donation; shared cache state unknown') from e
        self.traces.event(rid, 'admitted',
                          shared_prefix_tokens=shared_len)
        self._met.prompt_tokens.inc(true_len)
        return True

    def _prefill_chunk_step(self, pending: _PendingPrefill) -> None:
        """Run the next prompt chunk through the batch-1 forward; the
        chunk's K/V land at the cache cursor (sequential chunks, same
        cache1).

        Deliberately NOT under llama.slot_mode(): prefill must take
        the global-cursor/causal branch of run_cached_attention — a
        size-1 chunk traced in slot mode would scatter its K/V at the
        row's highest revealed kv_mask slot (true_len-1) instead of
        the cursor, silently corrupting the prompt."""
        chaos.maybe_raise('prefill_raise')
        chunk = self.prefill_chunk if self.prefill_chunk > 0 \
            else pending.pad
        start = pending.done
        size = min(chunk, pending.pad - start)
        tokens = jnp.asarray(pending.tokens[:, start:start + size])
        positions = jnp.arange(start, start + size,
                               dtype=jnp.int32)[None]
        kv_mask1 = jnp.asarray(pending.mask_row)[None]
        if self.kv_read_bucket > 0:
            # Chunk reads only need columns < start+size (causal) —
            # round up to the decode bucket granularity so early
            # chunks of a long prompt stop streaming the full
            # [1, kvh, max_seq_len, hd] rows.
            gran = self.kv_read_bucket
            bucket = min(self.max_seq_len,
                         ((start + size + gran - 1) // gran) * gran)
        else:
            bucket = 0
        prefill_key = (size, bucket)
        prefill_compiled = prefill_key not in self._prefill_keys_seen
        t_enter = time.perf_counter()
        logits, pending.cache1 = self._prefill1(
            self.params, pending.cache1, tokens, positions, kv_mask1,
            kv_bucket=bucket)
        if prefill_compiled:
            self._prefill_keys_seen.add(prefill_key)
            self._met.jit_compiles.labels(fn='prefill').inc()
            self._met.jit_compile_seconds.labels(fn='prefill').observe(
                time.perf_counter() - t_enter)
        last_idx = pending.true_len - 1
        if start <= last_idx < start + size:
            pending.last_row = logits[0, last_idx - start]
        pending.done = start + size
        self.traces.event(pending.rid, 'prefill_chunk')
        read_len = bucket if bucket else self.max_seq_len
        self._met.prefill_kernel_steps.labels(
            path=self.prefill_kernel).inc()
        self._met.prefill_read_bytes.observe(
            self._prefill_read_bytes_per_pos * read_len
            + self._prefill_epilogue_bytes_per_pos * read_len)
        if pending.done >= pending.true_len:
            # The rest of the padded length is masked-off zeros that
            # decode never reads (it writes at pad_len + generated):
            # skip those pure-padding chunks instead of burning ticks.
            pending.done = pending.pad

    def _finish_prefill(self, pending: _PendingPrefill) -> None:
        assert pending.last_row is not None
        try:
            self._finish_prefill_inner(pending)
        except Exception as e:  # pylint: disable=broad-except
            # The insert DONATES the shared cache: a mid-insert
            # failure leaves its buffers invalid.  Escalate past the
            # per-request containment — the supervisor must rebuild
            # device state (recover()).
            raise failures.SharedStateError(
                f'insert for request {pending.rid} failed mid-'
                f'donation; shared cache state unknown') from e

    def _finish_prefill_inner(self, pending: _PendingPrefill) -> None:
        if self.page_size:
            self._cache, self._last, self._kv_mask = \
                self._insert_paged(
                    self._cache, self._last, self._kv_mask,
                    pending.cache1, pending.last_row,
                    jnp.asarray(pending.mask_row),
                    jnp.asarray(pending.table_row),
                    jnp.int32(pending.slot_idx),
                    jnp.int32(pending.shared_len // self.page_size))
            # Publish the prompt's full pages so later requests with
            # the same (page-aligned) prefix prefill it once.
            self._alloc.register_prefix(
                pending.tokens[0, :pending.true_len].tolist(),
                pending.pages)
        else:
            self._cache, self._last, self._kv_mask = self._insert(
                self._cache, self._last, self._kv_mask, pending.cache1,
                pending.last_row, jnp.asarray(pending.mask_row),
                jnp.int32(pending.slot_idx))
        cfg = pending.cfg
        seed = cfg.seed if cfg.seed is not None else (
            hash((self._seed0, pending.rid)) & 0x7FFFFFFF)
        self._slots[pending.slot_idx] = _Slot(
            request_id=pending.rid, prompt_len=pending.true_len,
            pad_len=pending.pad, max_new=cfg.max_new_tokens,
            eos_id=cfg.eos_id, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p, seed=seed,
            pages=pending.pages,
            # Kept for every slot (not just ngram speculation): live
            # migration re-ships the prompt ids with the checkpoint.
            prompt_ids=pending.tokens[0, :pending.true_len].tolist(),
            pre_emitted=1 if pending.handoff else 0)
        self.traces.event(pending.rid, 'prefill_done')
        if pending.restore is not None:
            # Migrated slot: apply the checkpointed decode cursor and
            # resume mid-generation.  Every restored token was already
            # streamed by the victim replica, so none re-emit; the
            # next decode step folds (seed, generated) exactly as the
            # victim's would have — byte-identical continuation.  A
            # speculating engine's pending token is outputs[-1]
            # (pending form), so no seed sampling here either.
            r = pending.restore
            slot = self._slots[pending.slot_idx]
            slot.outputs = [int(t) for t in r['outputs']]
            slot.generated = int(r['generated'])
            slot.steps = int(r['steps'])
            slot.pre_emitted = len(slot.outputs)
            self.traces.event(pending.rid, 'migrate_resume',
                              generated=slot.generated)
            return
        if self.role == 'prefill':
            # Disaggregated prefill replica: sample + stream the seed
            # token, serialize the slot into the wire artifact, tear
            # the slot down.  This replica never decodes.
            self._handoff_export(pending)
            return
        if self.spec_k:
            self._spec_seed_slot(pending)

    def _spec_seed_slot(self, pending: _PendingPrefill) -> None:
        """Speculation bootstrap at prefill end: the verify step feeds
        [pending token, proposals...], so a fresh slot needs its first
        token NOW — sampled from the prefill logits with the same
        kernel and (seed, 0) key fold the fused decode step would use:
        the first token is bit-identical to plain decode's, and TTFT
        stops waiting for the first decode tick.  Draft mode also
        prefills the prompt into the draft's private cache here (a
        draft insert donates draft buffers, so this runs inside the
        _finish_prefill SharedStateError scope and a failure rebuilds
        both caches via recover())."""
        slot = self._slots[pending.slot_idx]
        cfg = pending.cfg
        if self._draft is not None:
            self._draft.admit(pending.slot_idx, pending.tokens,
                              pending.mask_row, pending.true_len,
                              pending.pad)
        else:
            slot.prompt_ids = \
                pending.tokens[0, :pending.true_len].tolist()
        max_k = top_k_bucket(cfg.top_k, self.config.vocab_size)
        use_top_p = cfg.top_p < 1.0
        tok = int(jax.device_get(self._seed_sample(
            pending.last_row, jnp.int32(slot.seed),
            jnp.float32(cfg.temperature), jnp.int32(cfg.top_k),
            jnp.float32(cfg.top_p), max_k=max_k, use_top_p=use_top_p,
            top_p_in_topk=bool(use_top_p and max_k > 0))))
        self._met.output_tokens.inc()
        self._commit_token(pending.slot_idx, tok)

    def _handoff_export(self, pending: _PendingPrefill) -> None:
        """role='prefill' epilogue, in place of keeping the slot:
        sample the request's FIRST token from the prefill logits
        (same (seed, 0) fold as the fused decode step, so the decode
        replica's re-derived draw is bit-identical), stream it to the
        local waiter, serialize the slot into the wire artifact
        (infer/handoff.py), and tear the slot down.  The normal
        insert/register_prefix above still ran, so the prompt's pages
        stay in THIS replica's prefix cache for later prompts
        (released pages are reclaimable, not erased).  A request that
        finishes ON its seed token (eos, or max_new_tokens == 1)
        completes here and nothing is exported.

        Runs inside the _finish_prefill SharedStateError scope: the
        teardown's block-table clear donates the shared cache, so a
        mid-donation failure escalates to the supervisor's recover()
        like any insert failure."""
        from skypilot_tpu.infer import handoff as handoff_lib
        slot_idx = pending.slot_idx
        slot = self._slots[slot_idx]
        cfg = pending.cfg
        rid = pending.rid
        t0 = time.perf_counter()
        max_k = top_k_bucket(cfg.top_k, self.config.vocab_size)
        use_top_p = cfg.top_p < 1.0
        tok = int(jax.device_get(self._seed_sample(
            pending.last_row, jnp.int32(slot.seed),
            jnp.float32(cfg.temperature), jnp.int32(cfg.top_k),
            jnp.float32(cfg.top_p), max_k=max_k, use_top_p=use_top_p,
            top_p_in_topk=bool(use_top_p and max_k > 0))))
        self._met.output_tokens.inc()
        if self._commit_token(slot_idx, tok):
            return
        trace = self.traces.get(rid)
        meta = {
            'model': self._model_name,
            'kv_cache_dtype': self.kv_cache_dtype,
            'page_size': self.page_size,
            'max_seq_len': self.max_seq_len,
            'true_len': pending.true_len,
            'pad': pending.pad,
            'prompt_ids': pending.tokens[0, :pending.true_len].tolist(),
            # The RESOLVED seed: the receiver cannot recompute the
            # hash((seed0, rid)) default — rids differ across
            # replicas.
            'seed': slot.seed,
            'seed_token': tok,
            'sampling': {
                'max_new_tokens': cfg.max_new_tokens,
                'temperature': cfg.temperature,
                'top_k': cfg.top_k,
                'top_p': cfg.top_p,
                'eos_id': cfg.eos_id,
            },
            'http_request_id': (trace.http_request_id
                                if trace is not None else None),
            'trace_parent': (trace.trace_parent
                             if trace is not None else None),
        }
        # Ship the batch-1 prefill cache's [.., :true_len, ..] slice:
        # cache1 holds the FULL prompt KV contiguously at prefill end
        # (prefix hits were hydrated into it), the insert above did
        # not donate it, and the seq axis is ndim-2 for every leaf
        # kind (the int8 scale rows carry a trailing size-1 axis).
        tensors: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                pending.cache1)[0]:
            names = _path_names(path)
            if names[-1] not in handoff_lib.KV_LEAF_NAMES:
                continue
            arr = np.asarray(jax.device_get(leaf))
            index = [slice(None)] * arr.ndim
            index[arr.ndim - 2] = slice(0, pending.true_len)
            tensors['/'.join(str(n) for n in names)] = \
                arr[tuple(index)]
        tensors[handoff_lib.LAST_ROW] = np.asarray(
            jax.device_get(pending.last_row), np.float32)
        raw_nbytes = sum(int(a.nbytes) for a in tensors.values())
        blob = handoff_lib.serialize_artifact(
            meta, tensors, compress=self._handoff_compress)
        n_pages = len(slot.pages)
        self._release_slot_pages(slot.pages, slot_idx)
        self._slots[slot_idx] = None
        with self._submit_lock:
            was_canceled = rid in self._canceled
            if was_canceled:
                self._canceled.discard(rid)
                event = None
                q = None
            else:
                self._results[rid] = slot.outputs
                self._handoffs[rid] = blob
                event = self._events.get(rid)
                q = self._stream_queues.get(rid)
            self._deadlines.pop(rid, None)
        if q is not None:
            q.put(self._STREAM_END)
        if event is not None:
            event.set()
        dt = time.perf_counter() - t0
        self.traces.event(rid, 'handoff_export', bytes=len(blob),
                          pages=n_pages, seconds=dt)
        trace = self.traces.finish(
            rid, 'cancelled' if was_canceled else 'handed_off',
            output_tokens=len(slot.outputs), decode_steps=slot.steps)
        if was_canceled:
            self._met.cancelled.inc()
        else:
            # TTFT is real on this side (the seed token streamed from
            # here); the decode-side latencies live on the decode
            # replica's trace, joined via http_request_id.
            self._met.observe_finished(trace)
            if self._handoff_met is not None:
                self._handoff_met['handoffs'].labels(
                    side='export').inc()
                self._handoff_met['export_seconds'].observe(dt)
                self._handoff_met['bytes'].labels(
                    form='wire').observe(len(blob))
                self._handoff_met['bytes'].labels(
                    form='raw').observe(raw_nbytes)
        self._met.inflight.set(self.traces.inflight_count)

    def take_handoff(self, request_id: int) -> Optional[bytes]:
        """Pop and return `request_id`'s serialized handoff artifact
        (None when the request completed locally — eos/budget on the
        seed token, cancel, or a role != 'prefill' engine).  The
        server calls this after the local stream ends to decide
        whether to relay.  Thread-safe."""
        with self._submit_lock:
            return self._handoffs.pop(request_id, None)

    # -- live migration (drain / preemption) --------------------------

    def _ensure_migration_metrics(self) -> Dict[str, Any]:
        if self._migration_met is None:
            self._migration_met = _migration_metrics(self.registry)
        return self._migration_met

    def can_migrate_out(self) -> bool:
        """Whether this engine's in-flight slots are checkpointable:
        paged cache (page ids ARE the KV addressing) and no draft
        model (a draft's private cache cannot be rebuilt
        mid-generation on the survivor)."""
        return bool(self.page_size) and self._draft is None

    def request_migrate_out(self) -> None:
        """Arm live migration: the scheduler's next step() checkpoints
        every occupied decode slot into a kind='slot' SKHO artifact,
        parks it for take_handoff(), and ends the local stream — the
        server relays each artifact to a survivor replica whose
        /handoff admission resumes it mid-generation.  Thread-safe
        (called from the server's drain handler); a non-migratable
        engine (contiguous cache, draft model) ignores the request
        and drains the classic way — by finishing locally."""
        if self.can_migrate_out():
            self._migrate_requested = True

    def _migrate_out_all(self) -> None:
        """Checkpoint every occupied slot (scheduler thread).  Slots
        still mid-prefill are left to finish locally — their KV is
        in a private batch-1 cache, not pool pages, and the drain
        window runs them to completion the classic way.  A failure
        checkpointing one slot keeps that slot decoding locally
        rather than killing its stream."""
        self._pipeline_join()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            try:
                self._migrate_slot(i)
            except failures.SharedStateError:
                raise
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    f'request {s.request_id}: migrate-out failed, '
                    f'continuing locally ({e!r})')

    def _migrate_slot(self, slot_idx: int) -> None:
        """Checkpoint ONE live slot into a kind='slot' artifact and
        tear it down, exactly as _handoff_export tears down a
        finished prefill: pages released (their prompt-prefix entries
        stay reclaimable for later prompts), blob parked for the
        server to relay, local stream ended.

        Checkpoint forms: a plain engine ships kv_len = pad +
        generated positions and the real last-logits row (the next
        token samples from it on the survivor with the same
        (seed, generated) fold).  A speculating engine holds the
        PENDING token's KV out of cache, so it ships kv_len = pad +
        generated - 1 and a zeros last row — the survivor's verify
        step re-feeds outputs[-1] as t_pend and samples in-graph."""
        from skypilot_tpu.infer import handoff as handoff_lib
        s = self._slots[slot_idx]
        rid = s.request_id
        t0 = time.perf_counter()
        pending_form = bool(self.spec_k)
        kv_len = s.pad_len + s.generated - (1 if pending_form else 0)
        n_used = -(-kv_len // self.page_size)
        table_row = np.zeros((self._pages_per_slot,), np.int32)
        table_row[:len(s.pages)] = s.pages
        # Stage the slot's pool pages into a contiguous batch-1 cache
        # with the SAME gather the prefix-hit path uses (traced page
        # count — no per-slot recompile), then slice the live extent
        # on host.  self._cache is read, not donated.
        cache1 = self._hydrate1(
            self._fresh_cache1(), self._cache, jnp.asarray(table_row),
            jnp.int32(n_used), jnp.int32(kv_len))
        tensors: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                cache1)[0]:
            names = _path_names(path)
            if names[-1] not in handoff_lib.KV_LEAF_NAMES:
                continue
            arr = np.asarray(jax.device_get(leaf))
            index = [slice(None)] * arr.ndim
            index[arr.ndim - 2] = slice(0, kv_len)
            tensors['/'.join(str(n) for n in names)] = \
                arr[tuple(index)]
        if pending_form:
            last_row = np.zeros((self.config.vocab_size,), np.float32)
        else:
            last_row = np.asarray(
                jax.device_get(self._last[slot_idx]), np.float32)
        tensors[handoff_lib.LAST_ROW] = last_row
        trace = self.traces.get(rid)
        meta = {
            'kind': handoff_lib.KIND_SLOT,
            'model': self._model_name,
            'kv_cache_dtype': self.kv_cache_dtype,
            'page_size': self.page_size,
            'max_seq_len': self.max_seq_len,
            'true_len': s.prompt_len,
            'pad': s.pad_len,
            'prompt_ids': list(s.prompt_ids or []),
            'seed': s.seed,
            'seed_token': (s.outputs[0] if s.outputs else -1),
            'sampling': {
                'max_new_tokens': s.max_new,
                'temperature': s.temperature,
                'top_k': s.top_k,
                'top_p': s.top_p,
                'eos_id': s.eos_id,
            },
            'kv_len': kv_len,
            'generated': s.generated,
            'outputs': list(s.outputs),
            'steps': s.steps,
            'pending_form': pending_form,
            'http_request_id': (trace.http_request_id
                                if trace is not None else None),
            'trace_parent': (trace.trace_parent
                             if trace is not None else None),
        }
        raw_nbytes = sum(int(a.nbytes) for a in tensors.values())
        blob = handoff_lib.serialize_artifact(
            meta, tensors, compress=self._handoff_compress)
        self._release_slot_pages(s.pages, slot_idx)
        self._slots[slot_idx] = None
        with self._submit_lock:
            was_canceled = rid in self._canceled
            if was_canceled:
                self._canceled.discard(rid)
                event = None
                q = None
            else:
                self._results[rid] = s.outputs
                self._handoffs[rid] = blob
                event = self._events.get(rid)
                q = self._stream_queues.get(rid)
            self._deadlines.pop(rid, None)
        if q is not None:
            q.put(self._STREAM_END)
        if event is not None:
            event.set()
        dt = time.perf_counter() - t0
        self.traces.event(rid, 'migrate_export', bytes=len(blob),
                          generated=s.generated, seconds=dt)
        self.traces.finish(
            rid, 'cancelled' if was_canceled else 'migrated',
            output_tokens=len(s.outputs), decode_steps=s.steps)
        if was_canceled:
            self._met.cancelled.inc()
        else:
            met = self._ensure_migration_metrics()
            met['migrations'].labels(side='out').inc()
            met['export_seconds'].observe(dt)
            met['bytes'].labels(form='wire').observe(len(blob))
            met['bytes'].labels(form='raw').observe(raw_nbytes)
        self._met.inflight.set(self.traces.inflight_count)

    def admit_handoff(self, blob: bytes,
                      stream: bool = False,
                      deadline_s: Optional[float] = None,
                      http_request_id: Optional[str] = None,
                      trace_parent: Optional[str] = None) -> int:
        """Accept one wire artifact from a prefill-role replica and
        enqueue it for mid-stream admission (ahead of the regular
        queue — its prefill cost was already spent elsewhere).
        Thread-safe like submit(); returns a request id for
        wait()/stream().  Raises HandoffVersionError on a wire-format
        mismatch and HandoffFormatError on anything malformed or
        geometry-incompatible, both BEFORE any engine state is
        created."""
        import queue as queue_mod
        import threading
        from skypilot_tpu.infer import handoff as handoff_lib
        if self.role == 'prefill':
            raise handoff_lib.HandoffFormatError(
                'prefill-role replicas do not ingest handoffs')
        meta, tensors = handoff_lib.deserialize_artifact(blob)
        self._validate_handoff(meta, tensors)
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            if deadline_s <= 0:
                raise ValueError(
                    f'deadline_s must be > 0, got {deadline_s}')
        with self._submit_lock:
            if self._fatal is not None:
                raise RuntimeError(
                    f'engine aborted: {self._fatal!r}') from self._fatal
            rid = self._next_rid
            self._next_rid += 1
            self._events[rid] = threading.Event()
            if stream:
                self._stream_queues[rid] = queue_mod.Queue()
            if deadline_s is not None:
                self._deadlines[rid] = time.monotonic() + deadline_s
            self._handoff_queue.append(
                (rid, meta, tensors, time.perf_counter()))
            depth = len(self._queue) + len(self._handoff_queue)
            trace = self.traces.begin(
                rid, prompt_tokens=int(meta['true_len']),
                http_request_id=(http_request_id
                                 or meta.get('http_request_id')))
            trace.trace_parent = (trace_parent
                                  or meta.get('trace_parent'))
        self._met.submitted.inc()
        self._met.queue_depth.set(depth)
        self._met.inflight.set(self.traces.inflight_count)
        return rid

    def _validate_handoff(self, meta: Dict[str, Any],
                          tensors: Dict[str, Any]) -> None:
        """Reject an artifact this engine cannot admit — model/cache
        geometry checks run against the engine's own abstract batch-1
        cache, BEFORE any allocation."""
        from skypilot_tpu.infer import handoff as handoff_lib

        def _bad(msg: str):
            return handoff_lib.HandoffFormatError(
                f'handoff artifact incompatible: {msg}')

        kind = meta.get('kind', handoff_lib.KIND_PREFILL)
        if kind == handoff_lib.KIND_KV_PREFIX:
            raise _bad('kv_prefix artifacts are served over '
                       'GET /kv_prefix, not POST /handoff')
        if meta['model'] != self._model_name:
            raise _bad(f"model {meta['model']!r} != {self._model_name!r}")
        if meta['kv_cache_dtype'] != self.kv_cache_dtype:
            raise _bad(f"kv_cache_dtype {meta['kv_cache_dtype']!r} != "
                       f'{self.kv_cache_dtype!r}')
        if int(meta['page_size']) != self.page_size:
            raise _bad(f"page_size {meta['page_size']} != "
                       f'{self.page_size}')
        if int(meta['max_seq_len']) != self.max_seq_len:
            raise _bad(f"max_seq_len {meta['max_seq_len']} != "
                       f'{self.max_seq_len}')
        true_len = int(meta['true_len'])
        pad = int(meta['pad'])
        max_new = int(meta['sampling']['max_new_tokens'])
        if not 1 <= true_len <= pad:
            raise _bad(f'true_len {true_len} outside [1, pad={pad}]')
        if max_new < 1 or pad + max_new > self.max_seq_len:
            raise _bad(f'pad {pad} + max_new_tokens {max_new} exceeds '
                       f'max_seq_len {self.max_seq_len}')
        if len(meta['prompt_ids']) != true_len:
            raise _bad(f"prompt_ids length {len(meta['prompt_ids'])} "
                       f'!= true_len {true_len}')
        extent = true_len
        if kind == handoff_lib.KIND_SLOT:
            # Migrated mid-generation slot: the shipped KV covers
            # kv_len positions (prompt + pad gap + generated tokens)
            # and the checkpoint form must match this engine's
            # stepping mode — a plain engine cannot hold a
            # speculation-pending token out of cache, and vice versa.
            if not self.page_size:
                raise _bad('slot migration requires a paged KV cache')
            if self._draft is not None:
                raise _bad('draft-model engines do not admit migrated '
                           'slots (the draft cache cannot be rebuilt '
                           'mid-generation)')
            pending_form = bool(meta['pending_form'])
            if pending_form != bool(self.spec_k):
                raise _bad(
                    f'checkpoint pending_form={pending_form} does not '
                    f'match this engine (spec_k={self.spec_k}) — '
                    f'migrate between like-stepping replicas')
            generated = int(meta['generated'])
            kv_len = int(meta['kv_len'])
            outputs = meta['outputs']
            if not isinstance(outputs, list) \
                    or len(outputs) != generated:
                raise _bad(f'outputs length != generated {generated}')
            if pending_form and generated < 1:
                raise _bad('pending-form checkpoint with no pending '
                           'token (generated must be >= 1)')
            if generated < 0 or generated >= max_new:
                raise _bad(f'generated {generated} outside '
                           f'[0, max_new_tokens={max_new})')
            if kv_len != pad + generated - (1 if pending_form else 0):
                raise _bad(f'kv_len {kv_len} inconsistent with pad '
                           f'{pad} + generated {generated}')
            extent = kv_len
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self._abstract_cache1)[0]:
            names = _path_names(path)
            if names[-1] not in handoff_lib.KV_LEAF_NAMES:
                continue
            key = '/'.join(str(n) for n in names)
            src = tensors.get(key)
            if src is None:
                raise _bad(f'missing cache leaf {key!r}')
            want = list(leaf.shape)
            want[len(want) - 2] = extent
            if list(src.shape) != want:
                raise _bad(f'leaf {key!r} shape {list(src.shape)} != '
                           f'{want}')
            if np.dtype(src.dtype) != np.dtype(leaf.dtype):
                raise _bad(f'leaf {key!r} dtype {src.dtype} != '
                           f'{np.dtype(leaf.dtype)}')
        last = tensors.get(handoff_lib.LAST_ROW)
        if last is None or last.shape != (self.config.vocab_size,):
            raise _bad(
                f'last_row missing or mis-shaped (want '
                f'({self.config.vocab_size},))')

    def _handoff_cache1(self, tensors: Dict[str, Any],
                        true_len: int) -> Any:
        """Rebuild a full-size batch-1 prefill cache from an
        artifact's shipped [.., :true_len, ..] slices: zeros
        everywhere, the shipped content at the origin of the seq axis
        (ndim-2 for every KV leaf kind).  The padded tail stays zero —
        those positions are masked forever on both sides, so the
        reconstruction feeds the NORMAL insert path unchanged."""
        from skypilot_tpu.infer import handoff as handoff_lib
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            self._fresh_cache1())
        out = []
        for path, leaf in flat:
            names = _path_names(path)
            if names[-1] in handoff_lib.KV_LEAF_NAMES:
                key = '/'.join(str(n) for n in names)
                src = jnp.asarray(np.ascontiguousarray(tensors[key]))
                leaf = jax.lax.dynamic_update_slice(
                    leaf, src.astype(leaf.dtype), (0,) * leaf.ndim)
            elif names[-1] == 'cache_index':
                # Cursor convention only — the slot-mode insert never
                # reads it, but keep it honest for debugging.
                leaf = jnp.full(leaf.shape, true_len, leaf.dtype)
            out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _admit_from_handoffs(self, free: List[int]) -> None:
        """Admit accepted handoff artifacts into free slots, AHEAD of
        the regular queue: their prefill already ran on another
        replica, so a free slot turns them into decode work
        immediately.  Backpressure mirrors _admit — requeue at the
        front and stop; decode keeps draining live slots whose
        completion returns pages."""
        now = time.monotonic()
        while free:
            with self._submit_lock:
                item = None
                if self._handoff_queue:
                    item = self._handoff_queue.popleft()
                    self._admitting_rid = item[0]
            if item is None:
                return
            rid = item[0]
            with self._submit_lock:
                deadline = self._deadlines.get(rid)
            if deadline is not None and now > deadline:
                with self._submit_lock:
                    self._admitting_rid = None
                    self._canceled.discard(rid)
                self._expire(rid)
                continue
            admitted = True
            try:
                admitted = self._admit_handoff(free[0], *item)
            except failures.SharedStateError:
                # Shared cache possibly invalidated mid-insert: NOT
                # containable (the pending is parked in _prefills for
                # recover() to find).
                raise
            except Exception as e:  # pylint: disable=broad-except
                with self._submit_lock:
                    self._canceled.discard(rid)
                self._fail_request(rid, failures.wrap_abort(rid, e))
                logger.warning(f'request {rid}: handoff admission '
                               f'failed, aborted ({e!r})')
                continue
            finally:
                with self._submit_lock:
                    self._admitting_rid = None
            if admitted:
                free.pop(0)
                continue
            with self._submit_lock:
                if rid in self._canceled:
                    self._canceled.discard(rid)
                    dropped_rid = rid
                else:
                    self._handoff_queue.appendleft(item)
                    dropped_rid = None
            if dropped_rid is not None:
                if self.traces.finish(dropped_rid, 'cancelled'):
                    self._met.cancelled.inc()
                self._met.inflight.set(self.traces.inflight_count)
            return

    def _admit_handoff(self, slot_idx: int, rid: int,
                       meta: Dict[str, Any], tensors: Dict[str, Any],
                       t_accept: float) -> bool:
        """Admit ONE deserialized artifact into slot `slot_idx`:
        page-id dedupe through the chain-hash prefix map, fresh pages
        for the rest, rebuild the batch-1 cache from the shipped
        slice, then converge into the NORMAL _finish_prefill path —
        the slot that comes out is indistinguishable from one this
        engine prefilled itself (speculation seeding included).
        Returns False on page backpressure without consuming
        anything."""
        from skypilot_tpu.infer import handoff as handoff_lib
        sampling = meta['sampling']
        cfg = SamplingConfig(
            max_new_tokens=int(sampling['max_new_tokens']),
            temperature=float(sampling['temperature']),
            top_k=int(sampling['top_k']),
            top_p=float(sampling['top_p']),
            eos_id=(None if sampling['eos_id'] is None
                    else int(sampling['eos_id'])),
            seed=int(meta['seed']))
        true_len = int(meta['true_len'])
        pad = int(meta['pad'])
        is_slot = meta.get('kind') == handoff_lib.KIND_SLOT
        # Slot checkpoints ship kv_len positions of KV (prompt + pad
        # gap + generated); prefill artifacts ship the prompt only.
        extent = int(meta['kv_len']) if is_slot else true_len
        prompt = [int(t) for t in meta['prompt_ids']]
        pages: List[int] = []
        table_row = None
        shared_len = 0
        shipped = deduped = 0
        if self.page_size:
            ps = self.page_size
            need = min(-(-(pad + cfg.max_new_tokens) // ps),
                       self._pages_per_slot)
            # Page-id dedupe: every page-aligned prompt page this
            # replica already holds is admitted BY REFERENCE — the
            # paged insert below redirects its columns to the null
            # page instead of rewriting a refcounted page.  Capped
            # one page short of the prompt's end, matching _admit.
            shared = self._alloc.lookup_prefix(
                prompt, max_pages=min((true_len - 1) // ps, need))
            fresh = self._alloc.alloc(need - len(shared))
            if fresh is None:
                for page in shared:
                    self._alloc.release(page)
                self._met.backpressure.inc()
                return False
            self._met.prefix_hits.inc(len(shared))
            self._met.prefix_misses.inc(len(fresh))
            pages = list(shared) + fresh
            shared_len = len(shared) * ps
            table_row = np.zeros((self._pages_per_slot,), np.int32)
            table_row[:len(pages)] = pages
            shipped, deduped = handoff_lib.prompt_page_split(
                prompt, len(shared), ps)
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, :true_len] = prompt
        mask_row = np.zeros((self.max_seq_len,), bool)
        mask_row[:true_len] = True
        if is_slot:
            # Reveal the checkpoint's generated-token KV as well —
            # decode positions live at [pad, pad + generated), and a
            # pending-form checkpoint holds the pending token's KV
            # out of cache (kv_len = pad + generated - 1).
            mask_row[pad:extent] = True
        try:
            cache1 = self._handoff_cache1(tensors, extent)
            last_row = jnp.asarray(np.ascontiguousarray(
                tensors[handoff_lib.LAST_ROW]))
        except BaseException:
            # Private-state failure: hand the pages back, let the
            # caller contain it to this rid.
            self._release_slot_pages(pages)
            raise
        pending = _PendingPrefill(
            slot_idx=slot_idx, rid=rid, cfg=cfg, true_len=true_len,
            pad=pad, tokens=tokens, mask_row=mask_row, cache1=cache1,
            done=pad, last_row=last_row, pages=pages,
            table_row=table_row, shared_len=shared_len, handoff=True,
            restore=({'generated': int(meta['generated']),
                      'outputs': meta['outputs'],
                      'steps': int(meta['steps'])}
                     if is_slot else None))
        self.traces.event(rid, 'admitted',
                          shared_prefix_tokens=shared_len)
        self.traces.event(rid, 'handoff_admitted',
                          shipped_pages=shipped, deduped_pages=deduped)
        # Park across the shared-cache insert (same protocol as
        # _admit): a mid-donation failure escalates and recover()
        # finds the pages here.
        self._prefills.append(pending)
        self._finish_prefill(pending)
        self._prefills.pop()
        if is_slot:
            met = self._ensure_migration_metrics()
            met['migrations'].labels(side='in').inc()
            met['admit_seconds'].observe(
                time.perf_counter() - t_accept)
        elif self._handoff_met is not None:
            self._handoff_met['handoffs'].labels(side='admit').inc()
            self._handoff_met['admit_seconds'].observe(
                time.perf_counter() - t_accept)
            self._handoff_met['pages'].labels(
                kind='shipped').inc(shipped)
            self._handoff_met['pages'].labels(
                kind='deduped').inc(deduped)
        return True

    def _commit_token(self, slot_idx: int, tok: int) -> bool:
        """Emit ONE token for the slot: append, stream, first-token
        trace event, eos/budget completion.  Returns True when the
        slot completed.  Runs once per TOKEN (not per step) so
        multi-token speculative commits keep per-token accounting —
        first_token fires on the first committed token, and TPOT stays
        tokens-based (observability/tracing.py)."""
        s = self._slots[slot_idx]
        s.outputs.append(tok)
        s.generated += 1
        if s.generated == 1:
            self.traces.event(s.request_id, 'first_token')
        q = self._stream_queues.get(s.request_id)
        if q is not None and s.generated > s.pre_emitted:
            # Handoff-admitted slots re-derive the seed token the
            # prefill replica already streamed (bit-identical draw):
            # account for it above, but do not emit it twice.
            q.put(tok)
        if (s.eos_id is not None and tok == s.eos_id) or \
                s.generated >= s.max_new:
            self._complete(slot_idx)
            return True
        return False

    def _release_slot_pages(self, pages: List[int],
                            slot_idx: Optional[int] = None) -> None:
        """Return a dead request's pages to the allocator and zero its
        device block-table row — a stale row would let the slot-mode
        write path scribble on pages already handed to another
        request (the zeroed row points at the reserved null page)."""
        if not self.page_size:
            return
        for page in pages:
            self._alloc.release(page)
        if slot_idx is not None:
            self._cache = self._clear_table(self._cache,
                                            jnp.int32(slot_idx))

    def _complete(self, slot_idx: int) -> None:
        slot = self._slots[slot_idx]
        assert slot is not None
        self._release_slot_pages(slot.pages, slot_idx)
        with self._submit_lock:
            was_canceled = slot.request_id in self._canceled
            if was_canceled:
                self._canceled.discard(slot.request_id)
                event = None
            else:
                self._results[slot.request_id] = slot.outputs
                event = self._events.get(slot.request_id)
            self._deadlines.pop(slot.request_id, None)
            q = self._stream_queues.get(slot.request_id)
        if q is not None:
            q.put(self._STREAM_END)
        if event is not None:
            event.set()
        self._slots[slot_idx] = None
        trace = self.traces.finish(
            slot.request_id,
            'cancelled' if was_canceled else 'finished',
            output_tokens=len(slot.outputs),
            decode_steps=slot.steps,
            first_step_idx=slot.first_step_idx,
            last_step_idx=slot.last_step_idx)
        if was_canceled:
            self._met.cancelled.inc()
        else:
            self._met.finished.inc()
            self._met.observe_finished(trace)
            total = trace.total_seconds() if trace is not None else None
            if total is not None:
                # Service-time EWMA feeding estimate_queue_wait_s();
                # only the scheduler thread writes it.
                prev = self._service_ewma_s
                self._service_ewma_s = total if prev is None \
                    else 0.8 * prev + 0.2 * total
        self._met.inflight.set(self.traces.inflight_count)

    def step(self) -> bool:
        """One scheduler tick: admit pending prompts into free slots,
        then one decode step for all occupied slots.  Returns False
        when fully idle (nothing queued, nothing occupied, nothing in
        flight).

        With `async_pipeline` (the default) the tick is double-
        buffered: the host front (admission, prefill chunks) runs
        while the previously dispatched step executes on device, then
        that step is joined/consumed and the next one dispatched —
        see _step_async for the ordering and the parity argument."""
        # Chaos fault points (no-ops unless SKYTPU_CHAOS is live):
        # a raise here is the transient step-failure class the
        # supervisor recovers from; a hang is the wedged-device class
        # the watchdog detects.  The pipeline fetch thread draws the
        # same points against the in-flight step (see
        # _pipeline_worker), so faults armed after a dispatch surface
        # on consume.
        chaos.maybe_raise('step_raise')
        chaos.maybe_hang('step_hang')
        ctx = self.mesh if self.mesh is not None \
            else contextlib.nullcontext()
        with ctx:
            if self._migrate_requested:
                # Drain-time live migration: checkpoint every occupied
                # slot out BEFORE this tick decodes — the scheduler
                # thread owns all slot/cache/allocator state here.
                self._migrate_requested = False
                self._migrate_out_all()
            if self.async_pipeline:
                return self._step_async()
            return self._step_sync()

    def _evict_canceled(self) -> None:
        with self._submit_lock:
            snapshot = set(self._canceled)
        evicted = 0
        for i, s in enumerate(self._slots):
            if s is not None and s.request_id in snapshot:
                self._release_slot_pages(s.pages, i)
                self._slots[i] = None
                if self.traces.finish(s.request_id, 'evicted',
                                      output_tokens=len(s.outputs),
                                      decode_steps=s.steps):
                    evicted += 1
        keep: List[_PendingPrefill] = []
        for p in self._prefills:
            if p.rid in snapshot:
                # Mid-prefill cancel: on the staging path the device
                # table row was never written (that happens at
                # _finish_prefill), so only the host-side pages need
                # returning.  A MIXED pending wrote its table row at
                # admission, so its row must be zeroed too before the
                # pages can be reallocated.
                self._release_slot_pages(
                    p.pages, p.slot_idx if p.mixed else None)
                if self.traces.finish(p.rid, 'evicted'):
                    evicted += 1
            else:
                keep.append(p)
        self._prefills = keep
        if evicted:
            self._met.evicted.inc(evicted)
            self._met.inflight.set(self.traces.inflight_count)
        # Entries with no slot are stale (e.g. admission raised after a
        # mid-prefill cancel) — drop them too, the set must not grow.
        with self._submit_lock:
            self._canceled -= snapshot

    def _schedule_front(self) -> None:
        """The host front of one tick: cancellation eviction, queue
        admission into free slots, and one prefill chunk per pending
        prompt.  Pure host scheduling plus prefill dispatches on
        PRIVATE batch-1 caches — in async mode this whole half runs
        while the previously dispatched decode step executes on
        device (insert/hydrate calls that touch the shared cache are
        functionally sequenced after the in-flight step through its
        future chain, so device-order correctness never depends on
        the join)."""
        self._evict_canceled()
        # top_k/top_p ride the decode jit as per-row vectors, so
        # admission is unconditional FIFO — greedy, top-k and top-p
        # requests interleave in one batch with no drain wait (the
        # round-3 head-of-line stall and per-(k,p) compile cache are
        # gone; the compile cache is bounded by the coarse max_k
        # power-of-two bucket x use_top_p keys).
        reserved = {p.slot_idx for p in self._prefills}
        free = [i for i, s in enumerate(self._slots)
                if s is None and i not in reserved]
        # Handoff artifacts first: their prefill already ran on a
        # prefill-role replica, so a free slot turns each into decode
        # work immediately (len() on the deque is a GIL-atomic peek;
        # the admission itself re-checks under the lock).
        if self._handoff_queue:
            self._admit_from_handoffs(free)
        now = time.monotonic()
        while free:
            with self._submit_lock:
                item = None
                if self._queue:
                    item = self._queue.popleft()
                    self._admitting_rid = item[0]
            if item is None:
                break
            rid, prompt, cfg, deadline = item
            if deadline is not None and now > deadline:
                # Expired in the queue: terminal before wasting a
                # prefill on output nobody is waiting for.
                with self._submit_lock:
                    self._admitting_rid = None
                    self._canceled.discard(rid)
                self._expire(rid)
                continue
            admitted = True
            try:
                admitted = self._admit(free[0], rid, prompt, cfg)
            except failures.SharedStateError:
                # Shared cache possibly invalidated mid-insert: NOT
                # containable.  The pending is parked in _prefills, so
                # the supervisor's recover() releases its pages and
                # fails the rid.
                raise
            except Exception as e:  # pylint: disable=broad-except
                # Admission failures touch only the request's private
                # prefill state (_admit released its pages): contain
                # to this rid, keep serving.
                with self._submit_lock:
                    self._canceled.discard(rid)
                self._fail_request(rid, failures.wrap_abort(rid, e))
                logger.warning(
                    f'request {rid}: admission failed, aborted ({e!r})')
                continue
            finally:
                with self._submit_lock:
                    self._admitting_rid = None
            if admitted:
                free.pop(0)
                continue
            # Paged admission backpressure: the pool can't cover this
            # request right now.  Requeue at the FRONT (FIFO order
            # preserved) and stop admitting this tick — decode below
            # keeps draining live slots, whose completion returns
            # pages.  A request canceled mid-backpressure is dropped
            # instead of requeued.
            with self._submit_lock:
                if item[0] in self._canceled:
                    self._canceled.discard(item[0])
                    dropped_rid = item[0]
                else:
                    self._queue.appendleft(item)
                    dropped_rid = None
            if dropped_rid is not None:
                # Canceled mid-backpressure: never reached a slot.
                if self.traces.finish(dropped_rid, 'cancelled'):
                    self._met.cancelled.inc()
                self._met.inflight.set(self.traces.inflight_count)
            break

        # One prefill chunk per tick for EVERY pending prompt
        # (round-robin, not head-only): several long prompts make
        # progress concurrently instead of queueing serially behind
        # the first one's full chunk sequence.  Decode below still
        # runs for live slots each tick, so live latency cost is one
        # chunk per pending prompt, bounded by n_slots.
        still_pending: List[_PendingPrefill] = []
        for pending in self._prefills:
            if pending.mixed:
                # Mixed pendings advance INSIDE decode steps
                # (_dispatch_mixed / _dispatch_spec), not on dedicated
                # prefill ticks.
                still_pending.append(pending)
                continue
            try:
                self._prefill_chunk_step(pending)
            except Exception as e:  # pylint: disable=broad-except
                # A chunk touches only the request's PRIVATE batch-1
                # cache — containable to this rid.  (_finish_prefill
                # below is NOT containable: it donates the shared
                # cache, so its exceptions propagate to the
                # supervisor, which rebuilds device state.)
                self._release_slot_pages(pending.pages)
                with self._submit_lock:
                    self._canceled.discard(pending.rid)
                self._fail_request(pending.rid,
                                   failures.wrap_abort(pending.rid, e))
                logger.warning(f'request {pending.rid}: prefill '
                               f'failed, aborted ({e!r})')
                continue
            if pending.done >= pending.pad:
                self._finish_prefill(pending)
            else:
                still_pending.append(pending)
        self._prefills = still_pending

    def _idle_gauges(self) -> None:
        """Keep the scheduler gauges honest while idle/prefilling."""
        self._met.live_slots.set(0)
        self._met.occupancy.set(0.0)
        self._met.queue_depth.set(len(self._queue)
                                  + len(self._handoff_queue))
        self._met.inflight.set(self.traces.inflight_count)

    def _step_sync(self) -> bool:
        """The synchronous tick: front, dispatch, fetch, consume —
        all inline on the scheduler thread.  This is the bit-exact
        reference stream the async pipeline is judged against."""
        self._schedule_front()
        occupied = [i for i, s in enumerate(self._slots)
                    if s is not None]
        mixed = [p for p in self._prefills if p.mixed]
        if not occupied and not mixed:
            self._idle_gauges()
            return bool(self._prefills) or bool(self._queue) \
                or bool(self._handoff_queue)
        if self.spec_k:
            handle = self._dispatch_spec(occupied, mixed)
        elif mixed:
            handle = self._dispatch_mixed(occupied, mixed)
        else:
            handle = self._dispatch_plain(occupied)
        self._fetch_handle(handle)
        if handle.error is not None:
            raise handle.error
        self._consume_step(
            handle,
            device_wait_s=handle.t_fetched - handle.t_dispatched)
        return True

    def _step_async(self) -> bool:
        """One double-buffered tick.  Ordering:

          1. front      — admission + prefill chunks (overlaps the
                          in-flight step N on device);
          2. join N     — wait for N's fetched tokens, then run every
                          commit/trace/metric on THIS thread;
          3. dispatch N+1 — build the step vectors from the
                          just-committed slot state and enqueue the
                          jitted step; hand the handle to the fetch
                          thread and return.

        Parity argument: commits always land before the next step's
        input vectors are built, so each dispatched step sees exactly
        the per-row state the synchronous loop would have given it.
        Admission observes slot completions one tick later than sync
        (they surface at the join), which can shift batch
        composition, but greedy decode is row-independent under the
        kv-mask so per-request token streams stay bit-identical —
        the tier-1 parity suite enforces this across cache modes and
        speculation modes.  Speculative rollback needs no extra care:
        rejection of a speculated window is pure kv_mask bookkeeping
        inside the verify step itself, so the one-step lookahead is
        squashed on device, never copied or replayed on host."""
        self._schedule_front()
        consumed = self._pipeline_join()
        if self._fatal is not None:
            return False
        occupied = [i for i, s in enumerate(self._slots)
                    if s is not None]
        mixed = [p for p in self._prefills if p.mixed]
        if not occupied and not mixed:
            self._idle_gauges()
            # A tick that consumed the final in-flight step did real
            # work (commits, completions): report busy so callers
            # observe the synchronous contract — False only from a
            # tick that did nothing at all.
            return consumed or bool(self._prefills) \
                or bool(self._queue) or bool(self._handoff_queue)
        if self.spec_k:
            handle = self._dispatch_spec(occupied, mixed)
        elif mixed:
            handle = self._dispatch_mixed(occupied, mixed)
        else:
            handle = self._dispatch_plain(occupied)
        self._pipeline_put(handle)
        return True

    # -- pipeline plumbing (fetch thread, join, fencing) ------------------

    def _fetch_handle(self, handle: _InflightStep) -> None:
        """Blocking device->host fetch of one handle's arrays — the
        only place in-flight step futures are synchronized.  Never
        raises: errors park on the handle for the consume side to
        re-raise on the scheduler thread."""
        try:
            handle.host = tuple(np.asarray(jax.device_get(a))
                                for a in handle.arrays)
        except BaseException as e:  # noqa: B036 — must not kill the thread
            handle.error = e
        finally:
            handle.t_fetched = time.perf_counter()
            handle.done.set()

    def _pipeline_worker(self) -> None:
        """Fetch-thread loop (prefetch_to_device idiom, train/data.py):
        take a handle, draw the step chaos points against it (so a
        fault armed while the step was in flight surfaces on consume),
        fetch, signal done.  Touches ONLY the handle — all engine
        state stays with the scheduler thread."""
        q = self._pipe_queue
        stop = self._pipe_stop
        while True:
            handle = q.get()
            if handle is _PIPE_STOP:
                break
            if stop.is_set():
                # Drain path: close() raced a queued handle.  Unpark
                # any joiner; nobody consumes the result.
                handle.error = RuntimeError(
                    'pipeline closed with a step in flight')
                handle.t_fetched = time.perf_counter()
                handle.done.set()
                continue
            try:
                if self._pipeline_delay_s:
                    # Test seam (slowed consumer).  Sleeps BEFORE the
                    # chaos draws so a test can arm a fault against a
                    # step that is already in flight.
                    time.sleep(self._pipeline_delay_s)
                chaos.maybe_raise('step_raise')
                chaos.maybe_hang('step_hang')
            except BaseException as e:  # noqa: B036 — park on handle
                handle.error = e
                handle.t_fetched = time.perf_counter()
                handle.done.set()
                continue
            self._fetch_handle(handle)

    def _pipeline_put(self, handle: _InflightStep) -> None:
        """Record `handle` as the (single) in-flight step and hand it
        to the fetch thread, starting the thread lazily on first
        use."""
        if self._pipe_thread is None or not self._pipe_thread.is_alive():
            self._pipe_queue = queue_lib.Queue()
            self._pipe_stop = threading.Event()
            self._pipe_thread = threading.Thread(
                target=self._pipeline_worker,
                name='skytpu-pipeline-fetch', daemon=True)
            self._pipe_thread.start()
        self._inflight = handle
        self._met.pipeline_depth.set(1)
        self._pipe_queue.put(handle)

    def _pipeline_join(self) -> bool:
        """Consume the in-flight step: wait for its fetch, measure the
        scheduler stall (async device-wait) and the host time hidden
        behind the step (overlap), then run all commits here on the
        scheduler thread.  Token commit timestamps — first_token
        trace events, TPOT, SLO verdicts — are therefore stamped at
        CONSUME time, never dispatch time: a slow consumer shows up
        in TPOT instead of being flattered away.  A fetch-side error
        re-raises here so transient/fatal classification and
        recover() flow exactly as in the synchronous loop.  Returns
        True when a step was consumed (the tick did real work)."""
        handle = self._inflight
        if handle is None:
            return False
        self._inflight = None
        t_join = time.perf_counter()
        while not handle.done.wait(0.5):
            # The fetch thread always signals: chaos hangs are
            # released by the watchdog/shutdown via release_hangs().
            pass
        waited = time.perf_counter() - t_join
        self._met.pipeline_depth.set(0)
        if self._fatal is not None:
            return False    # aborted while in flight: results are void
        if handle.error is not None:
            raise handle.error
        t_fetched = (handle.t_fetched if handle.t_fetched is not None
                     else t_join)
        overlap = max(0.0, min(t_join, t_fetched) - handle.t_dispatched)
        if overlap > 0.0:
            self._pipe_steps_overlapped += 1
        self._consume_step(handle, device_wait_s=waited,
                           overlap_s=overlap)
        return True

    def _pipeline_abandon(self) -> None:
        """Forget the in-flight step without consuming it.  Does NOT
        block: the fetch thread finishes with the handle's (possibly
        donation-invalidated) device arrays on its own schedule and
        nobody reads the result — stale commits are impossible
        because consumption only ever happens via _pipeline_join.
        recover()/abort() call this before rebuilding or abandoning
        device state."""
        if self._inflight is not None:
            self._inflight = None
            self._met.pipeline_depth.set(0)

    def close(self, timeout: float = 5.0) -> None:
        """Join the pipeline fetch thread (idempotent; a no-op on a
        synchronous or never-stepped engine).  Shutdown/drain fencing:
        after close() returns no step is in flight and — barring a
        wedged device_get, which is logged — no pipeline thread is
        alive."""
        self._pipeline_abandon()
        t = self._pipe_thread
        if t is None:
            return
        self._pipe_stop.set()
        self._pipe_queue.put(_PIPE_STOP)
        t.join(timeout)
        if t.is_alive():
            logger.warning(
                f'pipeline fetch thread still alive after {timeout}s '
                f'join (wedged device_get?)')
        else:
            self._pipe_thread = None

    def pipeline_info(self) -> Dict[str, Any]:
        """Pipeline block for /health?verbose=1: mode, current depth,
        fetch-thread liveness, and how many consumed steps actually
        hid host work behind the device.  Advisory racy reads — the
        scheduler thread owns the state."""
        t = self._pipe_thread
        return dict(
            mode='async' if self.async_pipeline else 'sync',
            depth=0 if self._inflight is None else 1,
            max_depth=1 if self.async_pipeline else 0,
            worker_alive=bool(t is not None and t.is_alive()),
            steps_overlapped=self._pipe_steps_overlapped,
        )

    # -- dispatch / consume halves of one decode step ---------------------

    def _dispatch_plain(self, occupied: List[int]) -> _InflightStep:
        from skypilot_tpu.models import llama

        b = self.n_slots
        cursors = np.zeros((b,), np.int32)
        rope = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b,), np.int32)
        gens = np.zeros((b,), np.int32)
        top_ks = np.zeros((b,), np.int32)
        top_ps = np.ones((b,), np.float32)
        for i in occupied:
            s = self._slots[i]
            cursors[i] = s.pad_len + s.generated
            rope[i] = s.prompt_len + s.generated
            active[i] = True
            temps[i] = s.temperature
            seeds[i] = s.seed
            gens[i] = s.generated
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
        max_k = top_k_bucket(int(top_ks.max()),
                             self.config.vocab_size)
        use_top_p = bool((top_ps < 1.0).any())
        # Static promise for the sort-free nucleus path: every row
        # that actually needs a top-p cutoff also ran top-k, so its
        # candidate set lives inside lax.top_k's sorted window.
        # Inactive slots carry the keep-all defaults (top_p=1, k=0)
        # and don't block the fast path.
        top_p_in_topk = bool(
            use_top_p and max_k > 0
            and (top_ks[top_ps < 1.0] > 0).all())
        if self.kv_read_bucket > 0:
            live = int(cursors[occupied].max()) + 1
            gran = self.kv_read_bucket
            bucket = min(self.max_seq_len,
                         ((live + gran - 1) // gran) * gran)
        else:
            bucket = self.max_seq_len
        decode_key = (max_k, use_top_p, top_p_in_topk, bucket)
        compiled = decode_key not in self._decode_keys_seen
        t_enter = time.perf_counter()
        with llama.slot_mode():
            tok_dev, self._last, self._cache, self._kv_mask = \
                self._decode(
                    self.params, self._cache, self._last, self._kv_mask,
                    jnp.asarray(rope), jnp.asarray(cursors),
                    jnp.asarray(seeds), jnp.asarray(gens),
                    jnp.asarray(active), jnp.asarray(temps),
                    jnp.asarray(top_ks), jnp.asarray(top_ps),
                    max_k=max_k, use_top_p=use_top_p,
                    top_p_in_topk=top_p_in_topk, kv_bucket=bucket)
        t_dispatched = time.perf_counter()
        if compiled:
            self._decode_keys_seen.add(decode_key)
        # Read-traffic estimate for THIS step, from the cursors already
        # on the host (no device reads): paged decode gathers each live
        # row's allocated pages; contiguous decode streams `bucket`
        # positions of every row.
        if self.page_size:
            ps = self.page_size
            read_bytes = self._read_bytes_per_page * sum(
                -(-(int(cursors[i]) + 1) // ps) for i in occupied)
            # XLA gather epilogue: every SLOT pays the shared bucketed
            # window (see decode_cache_read_bytes); 0.0 when fused.
            read_bytes += (self._epilogue_bytes_per_page
                           * self.n_slots * -(-bucket // ps))
        else:
            read_bytes = self._read_bytes_per_pos * bucket
        return _InflightStep(
            'plain', (tok_dev,), list(occupied),
            [self._slots[i].request_id for i in occupied],
            read_bytes, compiled, decode_key, t_enter, t_dispatched)

    def _mix_assignments(self, mixed: List[_PendingPrefill],
                         s_cap: int) -> List[int]:
        """FIFO split of the per-step prefill token budget across the
        mixed pendings: earlier admissions drain first (bounded TTFT
        for the head of the line), later ones wait their turn.  A row
        never takes more than s_cap tokens (the step's query width) or
        the tokens its prompt still needs."""
        left = self.prefill_mix_budget
        takes: List[int] = []
        for p in mixed:
            take = max(0, int(min(left, s_cap,
                                  p.true_len - p.done)))
            takes.append(take)
            left -= take
        return takes

    def _dispatch_mixed(self, occupied: List[int],
                        mixed: List[_PendingPrefill]) -> _InflightStep:
        """Dispatch half of one MIXED step: live decode rows sample
        and feed their next token exactly like _dispatch_plain, while
        up to --prefill-mix-budget prompt-chunk tokens ride the same
        s-query forward on the pending rows' slots — long prompts
        amortize across decode steps instead of stalling them.  Decode
        rows still commit exactly one token per step (the s>1 window
        beyond query 0 is masked garbage for them), so their streams
        stay bit-identical to unmixed plain decode."""
        from skypilot_tpu.models import llama

        b = self.n_slots
        s = self._mix_s
        cursors = np.zeros((b,), np.int32)
        rope = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b,), np.int32)
        gens = np.zeros((b,), np.int32)
        top_ks = np.zeros((b,), np.int32)
        top_ps = np.ones((b,), np.float32)
        tokens = np.zeros((b, s), np.int32)
        n_commit = np.zeros((b,), np.int32)
        last_pos = np.zeros((b,), np.int32)
        update_last = np.zeros((b,), bool)
        for i in occupied:
            sl = self._slots[i]
            cursors[i] = sl.pad_len + sl.generated
            rope[i] = sl.prompt_len + sl.generated
            active[i] = True
            temps[i] = sl.temperature
            seeds[i] = sl.seed
            gens[i] = sl.generated
            top_ks[i] = sl.top_k
            top_ps[i] = sl.top_p
            n_commit[i] = 1
            update_last[i] = True
        takes = self._mix_assignments(mixed, s)
        mix: List[Tuple[Any, int]] = []
        for p, take in zip(mixed, takes):
            if take <= 0:
                continue
            i = p.slot_idx
            # Chunk K/V lands at the cache cursor: slot == rope
            # position == done for a prompt row.
            cursors[i] = p.done
            rope[i] = p.done
            tokens[i, :take] = p.tokens[0, p.done:p.done + take]
            n_commit[i] = take
            seeding = p.done + take >= p.true_len
            update_last[i] = seeding
            last_pos[i] = take - 1 if seeding else 0
            mix.append((p, take))
        max_k = top_k_bucket(int(top_ks.max()),
                             self.config.vocab_size)
        use_top_p = bool((top_ps < 1.0).any())
        top_p_in_topk = bool(
            use_top_p and max_k > 0
            and (top_ks[top_ps < 1.0] > 0).all())
        work = occupied + [p.slot_idx for p, _ in mix]
        if self.kv_read_bucket > 0:
            # Query s-1 attends through position cursor + s - 1.
            live = int(cursors[work].max()) + s
            gran = self.kv_read_bucket
            bucket = min(self.max_seq_len,
                         ((live + gran - 1) // gran) * gran)
        else:
            bucket = self.max_seq_len
        decode_key = ('mixed', max_k, use_top_p, top_p_in_topk,
                      bucket)
        compiled = decode_key not in self._decode_keys_seen
        t_enter = time.perf_counter()
        with llama.slot_mode():
            tok_dev, self._last, self._cache, self._kv_mask = \
                self._mixed(
                    self.params, self._cache, self._last,
                    self._kv_mask, jnp.asarray(tokens),
                    jnp.asarray(rope), jnp.asarray(cursors),
                    jnp.asarray(seeds), jnp.asarray(gens),
                    jnp.asarray(active), jnp.asarray(n_commit),
                    jnp.asarray(last_pos), jnp.asarray(update_last),
                    jnp.asarray(temps), jnp.asarray(top_ks),
                    jnp.asarray(top_ps), max_k=max_k,
                    use_top_p=use_top_p, top_p_in_topk=top_p_in_topk,
                    kv_bucket=bucket)
        t_dispatched = time.perf_counter()
        if compiled:
            self._decode_keys_seen.add(decode_key)
        if self.page_size:
            ps = self.page_size
            read_bytes = self._read_bytes_per_page * sum(
                -(-(int(cursors[i]) + int(n_commit[i])) // ps)
                for i in work)
            read_bytes += (self._epilogue_bytes_per_page
                           * self.n_slots * -(-bucket // ps))
        else:
            read_bytes = self._read_bytes_per_pos * bucket
        return _InflightStep(
            'mixed', (tok_dev,), list(occupied),
            [self._slots[i].request_id for i in occupied],
            read_bytes, compiled, decode_key, t_enter, t_dispatched,
            mix=mix)

    def _dispatch_spec(self, occupied: List[int],
                       mixed: List[_PendingPrefill] = ()
                       ) -> _InflightStep:
        """Dispatch half of one speculative step for all occupied
        slots: propose k tokens per row (draft model, or n-gram
        self-drafting when no draft is configured) and enqueue the
        single s=k+1 verify forward.  The accepted prefix plus one
        sampled token per row commit on consume (_consume_step).
        Every slot here already holds its pending token
        (_spec_seed_slot emitted it at prefill end); rejection of a
        speculated window is squashed inside the verify step's
        kv_mask arithmetic, so the lookahead needs no host-side
        rollback or copies."""
        from skypilot_tpu.infer import speculative as spec_lib
        from skypilot_tpu.models import llama

        b = self.n_slots
        k = self.spec_k
        cursors = np.zeros((b,), np.int32)
        rope = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b,), np.int32)
        gens = np.zeros((b,), np.int32)
        top_ks = np.zeros((b,), np.int32)
        top_ps = np.ones((b,), np.float32)
        t_pend = np.zeros((b,), np.int32)
        n_prop = np.zeros((b,), np.int32)
        for i in occupied:
            s = self._slots[i]
            # The pending token's KV is not yet in cache: the verify
            # forwards it at the slot one BEFORE the plain-decode
            # cursor, together with the proposals behind it.
            cursors[i] = s.pad_len + s.generated - 1
            rope[i] = s.prompt_len + s.generated - 1
            active[i] = True
            temps[i] = s.temperature
            seeds[i] = s.seed
            gens[i] = s.generated
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
            t_pend[i] = s.outputs[-1]
            # Commits per verify = accepted + 1 <= n_prop + 1, and the
            # row may emit at most (max_new - generated) more tokens.
            n_prop[i] = min(k, s.max_new - s.generated - 1)
        # Mixed-in prefill rows ride the same s = k+1 verify window:
        # the chunk's first token takes the t_pend seat, the rest ride
        # the draft seats, and mix_real[i] = take drives the wholesale
        # reveal inside the verify (no acceptance test for prompt
        # tokens).  The row stays inactive so the decode-side
        # accept/commit arithmetic ignores it.
        takes = self._mix_assignments(mixed, k + 1)
        mix: List[Tuple[Any, int]] = []
        mix_real = np.zeros((b,), np.int32)
        mix_seed = np.zeros((b,), bool)
        for p, take in zip(mixed, takes):
            if take <= 0:
                continue
            i = p.slot_idx
            cursors[i] = p.done
            rope[i] = p.done
            t_pend[i] = p.tokens[0, p.done]
            cfg = p.cfg
            temps[i] = cfg.temperature
            seeds[i] = p.seed
            gens[i] = 0
            top_ks[i] = cfg.top_k
            top_ps[i] = cfg.top_p
            mix_real[i] = take
            mix_seed[i] = p.done + take >= p.true_len
            mix.append((p, take))
        max_k = top_k_bucket(int(top_ks.max()),
                             self.config.vocab_size)
        use_top_p = bool((top_ps < 1.0).any())
        top_p_in_topk = bool(
            use_top_p and max_k > 0
            and (top_ks[top_ps < 1.0] > 0).all())
        work = occupied + [p.slot_idx for p, _ in mix]
        if self.kv_read_bucket > 0:
            # Query k attends through position cursor + k.
            live = int(cursors[work].max()) + k + 1
            gran = self.kv_read_bucket
            bucket = min(self.max_seq_len,
                         ((live + gran - 1) // gran) * gran)
        else:
            bucket = self.max_seq_len
        if self._draft is not None:
            drafts = self._draft.propose(
                jnp.asarray(t_pend), jnp.asarray(rope),
                jnp.asarray(cursors), jnp.asarray(active),
                kv_bucket=bucket)
            self._spec_met['draft_steps'].inc(k + 1)
            if mix:
                # Prompt rows override the draft's proposals with the
                # real chunk tokens (the draft proposed garbage for
                # these inactive rows; its private cache row is reset
                # by draft.admit at seeding time).
                mix_drafts = np.zeros((b, k), np.int32)
                is_mix = np.zeros((b,), bool)
                for p, take in mix:
                    i = p.slot_idx
                    is_mix[i] = True
                    if take > 1:
                        mix_drafts[i, :take - 1] = \
                            p.tokens[0, p.done + 1:p.done + take]
                drafts = jnp.where(jnp.asarray(is_mix)[:, None],
                                   jnp.asarray(mix_drafts), drafts)
        else:
            drafts_np = np.zeros((b, k), np.int32)
            for i in occupied:
                s = self._slots[i]
                props = spec_lib.ngram_propose(
                    s.prompt_ids + s.outputs, int(n_prop[i]))
                drafts_np[i, :len(props)] = props
                n_prop[i] = len(props)
            for p, take in mix:
                if take > 1:
                    drafts_np[p.slot_idx, :take - 1] = \
                        p.tokens[0, p.done + 1:p.done + take]
            drafts = jnp.asarray(drafts_np)
        decode_key = (max_k, use_top_p, top_p_in_topk, bucket)
        compiled = decode_key not in self._spec_keys_seen
        t_enter = time.perf_counter()
        with llama.slot_mode():
            out_dev, counts_dev, self._cache, self._kv_mask = \
                self._spec_verify(
                    self.params, self._cache, self._kv_mask,
                    jnp.asarray(t_pend), drafts, jnp.asarray(rope),
                    jnp.asarray(cursors), jnp.asarray(n_prop),
                    jnp.asarray(seeds), jnp.asarray(gens),
                    jnp.asarray(active), jnp.asarray(temps),
                    jnp.asarray(top_ks), jnp.asarray(top_ps),
                    jnp.asarray(mix_real), jnp.asarray(mix_seed),
                    max_k=max_k, use_top_p=use_top_p,
                    top_p_in_topk=top_p_in_topk, kv_bucket=bucket)
        if self._draft is not None:
            # Reveal the committed window in the draft's mask too —
            # its scan already wrote KV for every speculated position.
            self._draft.commit(jnp.asarray(cursors), counts_dev,
                               jnp.asarray(active))
        t_dispatched = time.perf_counter()
        if compiled:
            self._spec_keys_seen.add(decode_key)
        if self.page_size:
            ps = self.page_size
            read_bytes = self._read_bytes_per_page * sum(
                -(-(int(cursors[i]) + k + 1) // ps) for i in work)
            read_bytes += (self._epilogue_bytes_per_page
                           * self.n_slots * -(-bucket // ps))
        else:
            read_bytes = self._read_bytes_per_pos * bucket
        return _InflightStep(
            'spec', (out_dev, counts_dev), list(occupied),
            [self._slots[i].request_id for i in occupied],
            read_bytes, compiled, decode_key, t_enter, t_dispatched,
            spec_n_prop=n_prop,
            spec_proposed=int(n_prop[occupied].sum()) if occupied
            else 0, mix=mix)

    def _consume_step(self, handle: _InflightStep,
                      device_wait_s: Optional[float] = None,
                      overlap_s: Optional[float] = None) -> None:
        """Consume half of one decode step: commit the fetched tokens
        into slot state and publish the step telemetry.  Always runs
        on the scheduler thread (inline in sync mode, at the join in
        async mode), so every commit timestamp is a consume-time
        stamp.  A slot whose request id changed since dispatch
        (evicted, aborted, recycled) is skipped — the guard that
        makes abort/cancel between dispatch and consume safe."""
        self._step_idx += 1
        step_idx = self._step_idx
        ctx_sum = 0
        spec_accepted = 0
        if handle.mode in ('plain', 'mixed'):
            toks = handle.host[0]
            n_tokens = None
            for i, rid in zip(handle.occupied, handle.rids):
                s = self._slots[i]
                if s is None or s.request_id != rid:
                    continue
                s.steps += 1
                # Live context this row's new token attended over
                # (ledger FLOP estimate) — host ints already in hand.
                ctx_sum += s.prompt_len + s.generated + 1
                if s.first_step_idx is None:
                    s.first_step_idx = step_idx
                    self.traces.annotate(rid, first_step_idx=step_idx)
                s.last_step_idx = step_idx
                self._commit_token(i, int(toks[i]))
        else:
            toks, counts = handle.host
            committed = 0
            accepted = 0
            for i, rid in zip(handle.occupied, handle.rids):
                n = int(counts[i])
                self._spec_met['accepted_len'].observe(n)
                accepted += n - 1
                s = self._slots[i]
                if s is None or s.request_id != rid:
                    continue
                s.steps += 1
                if s.first_step_idx is None:
                    s.first_step_idx = step_idx
                    self.traces.annotate(rid, first_step_idx=step_idx)
                s.last_step_idx = step_idx
                for j in range(n):
                    committed += 1
                    if self._commit_token(i, int(toks[i, j])):
                        break       # eos/budget: drop the tail
                # Post-commit context, n committed tokens' worth — an
                # analytic estimate, not a per-position integral.
                ctx_sum += n * (s.prompt_len + s.generated)
            self._spec_met['steps'].inc()
            self._spec_met['proposed'].inc(handle.spec_proposed)
            self._spec_met['accepted'].inc(accepted)
            self._spec_steps_n += 1
            self._spec_proposed_n += handle.spec_proposed
            self._spec_accepted_n += accepted
            n_tokens = committed
            spec_accepted = accepted
        mix_tokens = self._advance_mix(handle) if handle.mix else 0
        self._publish_step_metrics(
            len(handle.occupied), handle.read_bytes,
            dispatch_s=handle.t_dispatched - handle.t_enter,
            device_wait_s=device_wait_s,
            compiled=handle.compiled, n_tokens=n_tokens,
            host_overlap_s=overlap_s)
        led = self.step_ledger
        if led.enabled:
            free = used = None
            if self._alloc is not None:
                free = self._alloc.free_pages
                used = self._alloc.n_pages - 1 - free
            rec = led.record(
                step=step_idx, mode=handle.mode,
                t_enter=handle.t_enter,
                t_dispatch=handle.t_dispatched,
                t_join=handle.t_fetched,
                t_commit=time.perf_counter(),
                rows=len(handle.occupied),
                tokens=(len(handle.occupied) if n_tokens is None
                        else n_tokens),
                ctx_sum=ctx_sum, read_bytes=handle.read_bytes,
                mix_tokens=mix_tokens,
                spec_proposed=handle.spec_proposed,
                spec_accepted=spec_accepted,
                decode_kernel=self.decode_kernel,
                prefill_kernel=self.prefill_kernel,
                free_pages=free, used_pages=used,
                compiled=handle.compiled)
            if rec is not None:
                self._met.step_mfu.set(rec['mfu'])
                self._met.model_flops_per_token.set(
                    rec['flops_per_token'])

    def _advance_mix(self, handle: _InflightStep) -> int:
        """Consume-side bookkeeping for the prefill chunks that rode
        this step: advance each pending's cursor, and promote a
        prompt that just finished into a live _Slot.  A pending
        evicted between dispatch and consume is skipped — its device
        writes were garbage on released pages, which the eviction
        already zeroed out of the block table before any realloc."""
        advanced = 0
        for pending, take in handle.mix:
            if pending not in self._prefills:
                continue
            pending.done += take
            advanced += take
            self.traces.event(pending.rid, 'prefill_chunk')
            if pending.done >= pending.true_len:
                self._prefills.remove(pending)
                seed_tok = (int(handle.host[0][pending.slot_idx, 0])
                            if handle.mode == 'spec' else None)
                self._finish_mixed(pending, seed_tok)
        if advanced:
            self._met.prefill_mix_tokens.inc(advanced)
            self._met.prefill_mixed_steps.inc()
        return advanced

    def _finish_mixed(self, pending: _PendingPrefill,
                      seed_tok: Optional[int]) -> None:
        """Promote a drained mixed pending to a live slot.  The
        prompt's K/V is already in the shared cache (chunks wrote in
        place) and `last` already holds the final true token's logits
        (the seeding row's update_last/last_pos), so there is no
        insert and no donation hazard here.  On a spec engine the
        first output token was sampled IN the final chunk's verify
        step (mix_seed) and arrives via seed_tok — the same
        (seed, gens=0) key fold _spec_seed_slot uses, so streams stay
        bit-identical to the unmixed path."""
        cfg = pending.cfg
        self._slots[pending.slot_idx] = _Slot(
            request_id=pending.rid, prompt_len=pending.true_len,
            pad_len=pending.pad, max_new=cfg.max_new_tokens,
            eos_id=cfg.eos_id, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p, seed=pending.seed,
            pages=pending.pages,
            prompt_ids=pending.tokens[0, :pending.true_len].tolist())
        if self.page_size:
            self._alloc.register_prefix(
                pending.tokens[0, :pending.true_len].tolist(),
                pending.pages)
        self.traces.event(pending.rid, 'prefill_done')
        if self.spec_k:
            slot = self._slots[pending.slot_idx]
            if self._draft is not None:
                self._draft.admit(pending.slot_idx, pending.tokens,
                                  pending.mask_row, pending.true_len,
                                  pending.pad)
            else:
                slot.prompt_ids = \
                    pending.tokens[0, :pending.true_len].tolist()
            self._met.output_tokens.inc()
            self._commit_token(pending.slot_idx, int(seed_tok))

    def _publish_step_metrics(self, n_occupied: int,
                              read_bytes: float,
                              dispatch_s: Optional[float] = None,
                              device_wait_s: Optional[float] = None,
                              compiled: bool = False,
                              n_tokens: Optional[int] = None,
                              host_overlap_s: Optional[float] = None
                              ) -> None:
        """Per-step telemetry: gauges + counters from host-side state
        already in hand.  This is the entire per-step telemetry cost —
        the overhead guard test times it directly against a measured
        decode step, so keep it allocation-free.

        `dispatch_s` is the wall time inside the jitted decode call;
        on a first-sight static key (`compiled=True`) that includes
        trace+compile and is booked as a compile, otherwise it is the
        async-dispatch cost ROADMAP item 3 will be judged against.
        `device_wait_s` is the scheduler thread's block on the step's
        results (device_get inline in sync mode, the pipeline join in
        async mode); `host_overlap_s` is the host work the async
        pipeline hid behind the in-flight step.

        `n_tokens` is the number of tokens the step actually emitted;
        it defaults to one per occupied slot (plain decode), and the
        speculative step passes its multi-token commit total — token
        accounting must never assume 1 token per step."""
        m = self._met
        m.steps.inc()
        m.decode_kernel_steps.labels(path=self.decode_kernel).inc()
        m.slot_steps.inc(n_occupied)
        m.output_tokens.inc(n_occupied if n_tokens is None
                            else n_tokens)
        m.live_slots.set(n_occupied)
        m.occupancy.set(n_occupied / self.n_slots)
        m.queue_depth.set(len(self._queue))
        m.inflight.set(self.traces.inflight_count)
        m.read_bytes.observe(read_bytes)
        if dispatch_s is not None:
            if compiled:
                m.jit_compiles.labels(fn='decode').inc()
                m.jit_compile_seconds.labels(fn='decode').observe(
                    dispatch_s)
            else:
                m.dispatch_seconds.observe(dispatch_s)
        if device_wait_s is not None:
            m.device_wait_seconds.observe(device_wait_s)
            if self._mesh_devices > 1:
                m.decode_collective_seconds.observe(device_wait_s)
        if host_overlap_s is not None:
            m.host_overlap_seconds.observe(host_overlap_s)
        if self._alloc is not None:
            free = self._alloc.free_pages
            m.free_pages.set(free)
            used = self._alloc.n_pages - 1 - free  # page 0 reserved
            if used > self._pages_used_peak:
                self._pages_used_peak = used
                m.pages_used_peak.set(used)
            cann = self._alloc.cannibalized_total
            if cann > self._cannibalized_seen:
                m.cannibalized.inc(cann - self._cannibalized_seen)
                self._cannibalized_seen = cann
            if self._fleet_met is not None:
                # Same diff pattern as cannibalized: lifetime counters
                # read lock-free (plain int reads; the host tier's
                # writers hold its lock, we only ever under-read).
                fm = self._fleet_met
                hc = self._host_cache
                if self._alloc.spilled_total > self._spilled_seen:
                    fm['spilled_pages'].inc(
                        self._alloc.spilled_total - self._spilled_seen)
                    self._spilled_seen = self._alloc.spilled_total
                if self._spilled_bytes > self._spilled_bytes_seen:
                    fm['spilled_bytes'].inc(
                        self._spilled_bytes - self._spilled_bytes_seen)
                    self._spilled_bytes_seen = self._spilled_bytes
                if self._rehydrated_pages > self._rehydrated_seen:
                    fm['rehydrated_pages'].inc(
                        self._rehydrated_pages - self._rehydrated_seen)
                    self._rehydrated_seen = self._rehydrated_pages
                if self._saved_tokens > self._saved_seen:
                    fm['saved_tokens'].inc(
                        self._saved_tokens - self._saved_seen)
                    self._saved_seen = self._saved_tokens
                if hc.hits_total > self._fleet_hits_seen:
                    fm['hits'].inc(hc.hits_total
                                   - self._fleet_hits_seen)
                    self._fleet_hits_seen = hc.hits_total
                if hc.misses_total > self._fleet_misses_seen:
                    fm['misses'].inc(hc.misses_total
                                     - self._fleet_misses_seen)
                    self._fleet_misses_seen = hc.misses_total
                if hc.evicted_pages_total > self._fleet_evicted_seen:
                    fm['evicted_pages'].inc(
                        hc.evicted_pages_total
                        - self._fleet_evicted_seen)
                    self._fleet_evicted_seen = hc.evicted_pages_total
                fm['stored_bytes'].set(hc.stored_bytes)
                fm['stored_pages'].set(hc.stored_pages)

    def run_until_idle(self) -> None:
        while self.step():
            pass

    # -- admission outlook (shedding / drain signals) ---------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue) + len(self._handoff_queue)

    def is_idle(self) -> bool:
        """True when nothing is queued, prefilling, or slot-resident.
        Advisory (racy reads from other threads): drain polls it."""
        return not self._queue and not self._handoff_queue \
            and not self._prefills \
            and all(s is None for s in self._slots)

    def estimate_queue_wait_s(self) -> float:
        """Rough admission-wait estimate for a NEW request: queued
        work divided into n_slots-wide waves times the EWMA of recent
        submit->finish service times.  0.0 with no history yet — the
        shed check then falls back to queue depth alone."""
        ewma = self._service_ewma_s
        if not ewma:
            return 0.0
        return (len(self._queue) / self.n_slots) * ewma

    # -- router / health surface ------------------------------------------
    def publish_memory_watermarks(self) -> None:
        """Scrape-time (NOT per-step) device-memory watermark: sets
        skytpu_device_memory_peak_bytes from the first local device's
        allocator stats.  Backends without memory_stats (CPU) leave
        the gauge at 0 — the call is always safe."""
        _publish_device_memory_peak(self._met)

    def allocator_leak_report(self) -> Optional[str]:
        """None when the page pool is clean (or unpaged), else the
        allocator's description of what leaked.  The verbose health
        endpoint exposes this so the chaos e2e can assert survivors
        stayed leak-free without reaching into process internals."""
        if self._alloc is None:
            return None
        return self._alloc.leak_report()

    def free_pages(self) -> Optional[int]:
        """Allocatable KV pages right now (None when unpaged)."""
        if self._alloc is None:
            return None
        return self._alloc.free_pages

    def ledger_info(self) -> Dict[str, Any]:
        """Step-ledger config/state block for /health?verbose=1."""
        return self.step_ledger.info()

    def speculation_info(self) -> Optional[Dict[str, Any]]:
        """Speculation summary for /health?verbose=1 (None when
        disabled): mode, spec_k, cumulative step/proposal/acceptance
        counts, and the acceptance rate the router/fleet views key
        off.  Advisory racy reads — the decode thread owns the
        counters."""
        if not self.spec_k:
            return None
        proposed = self._spec_proposed_n
        return dict(
            mode='draft' if self._draft is not None else 'ngram',
            draft_model=(self._draft.model_name
                         if self._draft is not None else None),
            spec_k=self.spec_k,
            steps=self._spec_steps_n,
            proposed_tokens=proposed,
            accepted_tokens=self._spec_accepted_n,
            acceptance_rate=(self._spec_accepted_n / proposed
                             if proposed else None),
        )

    def prefix_routing_key(self, prompt_ids: Sequence[int]
                           ) -> Optional[int]:
        """The prefix-affinity key a router would compute for this
        prompt (None when unpaged).  Same function, same page size —
        the engine-side anchor for router affinity tests."""
        if not self.page_size:
            return None
        from skypilot_tpu.infer import paging as paging_lib
        return paging_lib.routing_key(prompt_ids, self.page_size)

    # -- convenience (request-level API parity) ---------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 sampling: Optional[SamplingConfig] = None
                 ) -> List[List[int]]:
        """Submit `prompts` (any count — more than n_slots queues) and
        drive the loop until all finish."""
        rids = [self.submit(p, sampling) for p in prompts]
        pending = set(rids)
        while pending:
            if not self.step():
                break
            pending = {r for r in rids if not self._events[r].is_set()}
        out = [self.wait(r, timeout=0.001) for r in rids]
        # A role='prefill' engine parks a handoff artifact per request
        # (nobody relays it on this synchronous path — e.g. the
        # server's warmup generate): drain them so they cannot leak.
        for r in rids:
            self.take_handoff(r)
        return out


class InferenceEngine:
    """Batched KV-cache generation over a (possibly sharded) model."""

    def __init__(self, model: str = 'llama-tiny',
                 mesh=None,
                 params: Any = None,
                 checkpoint_dir: Optional[str] = None,
                 max_batch_size: int = 4,
                 max_seq_len: Optional[int] = None,
                 model_overrides: Optional[Dict[str, Any]] = None,
                 param_dtype: Any = jnp.bfloat16,
                 prefill_bucket: int = 64,
                 quantize: Optional[str] = None,
                 kv_cache_dtype: str = 'auto',
                 page_size: int = 0,
                 max_pages: int = 0,
                 seed: int = 0,
                 registry: Optional[metrics_lib.Registry] = None) -> None:
        if quantize not in (None, 'int8'):
            raise ValueError(f"quantize must be None or 'int8', got "
                             f'{quantize!r}.')
        if kv_cache_dtype not in ('auto', 'int8'):
            raise ValueError(f"kv_cache_dtype must be 'auto' or "
                             f"'int8', got {kv_cache_dtype!r}.")
        if page_size:
            if page_size < 1 or page_size & (page_size - 1):
                raise ValueError(f'page_size must be a power of two, '
                                 f'got {page_size}')
            if max(1, prefill_bucket) % page_size:
                raise ValueError(
                    f'page_size ({page_size}) must divide '
                    f'prefill_bucket ({prefill_bucket})')
        elif max_pages:
            raise ValueError('max_pages requires page_size > 0')
        self.quantize = quantize
        overrides = dict(model_overrides or {})
        overrides.update(decode=True, remat=False)
        # Explicit model_overrides win; otherwise the engine flag
        # reaches run_cached_attention through the model config.
        overrides.setdefault('kv_cache_dtype', kv_cache_dtype)
        if quantize:
            # Scanned layers would (a) give stacked kernels a leading
            # layer axis that breaks per-output-channel scales and
            # (b) force the dequantized tree to materialize as the
            # scan while-loop's input each step, erasing the HBM win.
            # Unscanned decode graphs fuse dequant into each consumer.
            overrides['scan_layers'] = False
        overrides.setdefault('param_dtype', param_dtype)
        if max_seq_len is not None:
            overrides['max_seq_len'] = max_seq_len
        if page_size:
            # Two-pass build: peek the config for max_seq_len, then
            # size the page pool.  Explicit model_overrides win, like
            # kv_cache_dtype above.
            _, peek = models_lib.get_model(model, **overrides)
            if peek.max_seq_len % page_size:
                raise ValueError(
                    f'page_size ({page_size}) must divide max_seq_len '
                    f'({peek.max_seq_len})')
            # Default pool: every slot can fill its row, +1 for the
            # reserved null page — capacity-neutral vs contiguous;
            # smaller max_pages oversubscribes (admission backpressure).
            n_pages = max_pages if max_pages else \
                max_batch_size * (peek.max_seq_len // page_size) + 1
            overrides.setdefault('kv_page_size', page_size)
            overrides.setdefault('kv_n_pages', n_pages)
        self.model, self.config = models_lib.get_model(model, **overrides)
        self._model_name, self._overrides = model, dict(overrides)
        self.kv_cache_dtype = getattr(self.config, 'kv_cache_dtype',
                                      'auto')
        self.page_size = getattr(self.config, 'kv_page_size', 0)
        self.n_pages = getattr(self.config, 'kv_n_pages', 0)
        self.max_batch = max_batch_size
        self.max_seq_len = self.config.max_seq_len
        self.prefill_bucket = max(1, prefill_bucket)
        self.mesh = mesh

        init_tokens = jnp.zeros((max_batch_size, 1), jnp.int32)
        rng = jax.random.PRNGKey(seed)

        def _init():
            return self.model.init(rng, init_tokens)

        if self.page_size:
            # Paged cache vars only exist on the slot-mode trace (the
            # batch-wide kv_mask drives per-row write positions), so
            # the abstract cache must be shaped under that mode: page
            # pools [n_pages, kvh, page_size, hd] + per-slot block
            # tables instead of contiguous [B, kvh, S, hd] rows.
            from skypilot_tpu.models import llama as llama_lib
            kv_mask0 = jnp.zeros((max_batch_size, self.max_seq_len),
                                 bool)

            def _init_paged():
                return self.model.init(rng, init_tokens, None, kv_mask0)

            with llama_lib.slot_mode():
                abstract = jax.eval_shape(_init_paged)
        else:
            abstract = jax.eval_shape(_init)
        if mesh is not None:
            param_shardings = sharding_lib.unbox(
                sharding_lib.params_to_shardings(mesh,
                                                 abstract['params']))
            cache_shardings = jax.tree.map(
                functools.partial(_cache_sharding, mesh,
                                  n_pages=self.n_pages),
                abstract['cache'])
        else:
            param_shardings = cache_shardings = None

        self._cache_shardings = cache_shardings
        self._abstract_cache = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            sharding_lib.unbox(abstract['cache']))
        # Pool kv-head count, read off the abstract cache (NOT the
        # config: DeepSeek's absorbed-latent paged pool is kvh == 1
        # regardless of n_heads) — drives decode-kernel resolution and
        # the /health sharding block.
        self.pool_kvh = 0
        for leaf in jax.tree.leaves(self._abstract_cache):
            if self.n_pages and leaf.ndim == 4 \
                    and leaf.shape[0] == self.n_pages:
                self.pool_kvh = leaf.shape[1]
                break
            if self.n_pages and leaf.ndim == 5 \
                    and leaf.shape[1] == self.n_pages:
                self.pool_kvh = leaf.shape[2]
                break
            if not self.n_pages and leaf.ndim == 4:
                self.pool_kvh = leaf.shape[1]
                break
            if not self.n_pages and leaf.ndim == 5:
                self.pool_kvh = leaf.shape[2]
                break
        already_quantized = False
        self.loaded_real_weights = True
        if params is not None:
            if self.quantize and isinstance(params, dict) \
                    and 'layers' in params:
                # Scanned-layout weights (trainer default) must be
                # unstacked BEFORE placement: param_shardings follow
                # this engine's unscanned tree.
                params = unstack_scanned_params(params,
                                                self.config.n_layers)
            if self.quantize == 'int8':
                # Quantize BEFORE mesh placement: device_put-ing the
                # float tree onto the mesh only to replace it with the
                # int8 tree would double init-time host->HBM traffic
                # and transiently hold both copies.  Cast to
                # param_dtype first (same as _place) so q8/scale are
                # derived from exactly the values float serving uses.
                cast = jax.tree.map(
                    lambda x: jnp.asarray(x, self.config.param_dtype)
                    if jnp.issubdtype(jnp.asarray(x).dtype,
                                      jnp.floating)
                    else jnp.asarray(x), params)
                q = jax.tree.map(jnp.asarray,
                                 quantize_params_int8(cast))
                if mesh is not None:
                    q = jax.device_put(
                        q, quantized_param_shardings(
                            mesh, param_shardings, q))
                self.params = q
                already_quantized = True
            else:
                self.params = self._place(params, param_shardings)
        elif checkpoint_dir is not None:
            self.params = self._load_checkpoint(checkpoint_dir,
                                                abstract['params'],
                                                param_shardings)
        else:
            # Callers gate on this (the server refuses to expose an
            # OpenAI endpoint over noise without an explicit opt-in).
            self.loaded_real_weights = False
            logger.warning('InferenceEngine: no params/checkpoint given '
                           '— serving randomly initialized weights '
                           '(tests/dev only).')

            def _init_params():
                return sharding_lib.unbox(_init())['params']
            if mesh is not None:
                self.params = jax.jit(
                    _init_params, out_shardings=param_shardings)()
            else:
                self.params = _init_params()
        if self.quantize == 'int8' and not already_quantized:
            if isinstance(self.params, dict) and 'layers' in self.params:
                # Caller handed scanned-layout weights (the trainer
                # default); this engine runs unscanned.
                self.params = unstack_scanned_params(
                    self.params, self.config.n_layers)
            self.params = jax.tree.map(  # materialize, then quantize
                jnp.asarray, quantize_params_int8(self.params))
            if mesh is not None:
                # {q8, scale} leaves carry NamedShardings derived from
                # the float kernels' logical rules — tensor-parallel
                # int8 decode shards exactly like its float twin.
                self.params = jax.device_put(
                    self.params,
                    quantized_param_shardings(mesh, param_shardings,
                                              self.params))

        def _forward(p, cache, tokens, positions, kv_mask):
            p = maybe_dequantize_params(p, self.config.param_dtype)
            logits, mutated = self.model.apply(
                {'params': p, 'cache': cache}, tokens, positions,
                kv_mask, mutable=['cache'])
            return logits, mutated['cache']

        # Prefill: donate the cache buffers (they are replaced).
        self._prefill = jax.jit(_forward, donate_argnums=(1,))

        def _decode_step(p, cache, last_logits, kv_mask, lengths,
                         prefill_len, step, rng, active,
                         temperature: float, top_k: int, top_p: float):
            """Fused: sample from last logits -> reveal the new slot ->
            one-token forward.  Returns (token, next logits, cache,
            kv_mask).

            The new token's K/V land at the cache *cursor*
            (prefill_len + step — prompts are right-padded to
            prefill_len), while its rope position is the row's true
            length + step; the kv mask bridges the difference.

            Only the fields sampling actually uses are static compile
            keys — max_new_tokens / eos_id live in the host loop and
            must not fragment the compile cache.
            """
            step_rng = jax.random.fold_in(rng, step)
            next_tok = sample_logits(
                last_logits, step_rng,
                SamplingConfig(temperature=temperature, top_k=top_k,
                               top_p=top_p))
            slot = prefill_len + step
            kv_mask = jax.lax.dynamic_update_slice(
                kv_mask, active[:, None], (0, slot))
            positions = (lengths + step)[:, None]
            logits, cache = _forward(p, cache, next_tok[:, None],
                                     positions, kv_mask)
            return next_tok, logits[:, 0], cache, kv_mask

        self._decode = jax.jit(
            _decode_step,
            static_argnames=('temperature', 'top_k', 'top_p'),
            donate_argnums=(1, 3))
        self._rng = jax.random.PRNGKey(seed + 1)
        self._generation = 0

        # Telemetry.  Metric updates are host-side bookkeeping only;
        # nothing below ever forces a device value.
        self.registry = (registry if registry is not None
                         else metrics_lib.get_registry())
        self._met = _ServingMetrics(self.registry)
        self._met.mesh_devices.set(mesh.devices.size
                                   if mesh is not None else 1)
        self.traces = _trace_store_from_env()
        # Contiguous decode streams every cache position of the row;
        # precompute bytes-per-position once so the per-step estimate
        # is a single multiply.  (Paged serving goes through
        # ContinuousBatchingEngine, which owns its own constants.)
        if self.page_size:
            self._read_bytes_per_pos = 0.0
        else:
            self._read_bytes_per_pos = self.cache_read_bytes_per_step(
                context=1)['grouped_bytes']
        # Per-step performance ledger (shares the continuous engine's
        # env construction; see observability/ledger.py).  The step
        # counter lives outside the ledger so /traces step-index joins
        # survive SKYTPU_STEP_LEDGER=0.
        self._step_idx = 0
        self.step_ledger = _step_ledger_from_env(
            self.config, self._model_name,
            mesh.devices.size if mesh is not None else 1)

    def ledger_info(self) -> Dict[str, Any]:
        """Static ledger facts for /health?verbose=1 and /profile."""
        return self.step_ledger.info()

    # -- weights -----------------------------------------------------------
    def _place(self, params, shardings):
        cast = jax.tree.map(
            lambda x: jnp.asarray(x, self.config.param_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else
            jnp.asarray(x), params)
        if shardings is None:
            return cast
        return jax.device_put(cast, shardings)

    def _load_checkpoint(self, directory: str, abstract_params,
                         shardings):
        """Load params from a trainer checkpoint (train/checkpoint.py
        layouts, split or legacy) — params only, restored directly into
        the serving shardings."""
        from skypilot_tpu.train import checkpoint as ckpt_lib
        manager = ckpt_lib.make_manager(directory)
        latest = manager.latest_step()
        if latest is None:
            raise FileNotFoundError(
                f'no checkpoint found under {directory!r}')
        abstract = sharding_lib.unbox(abstract_params)
        if shardings is not None:
            abs_tree = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abstract, shardings)
        else:
            # Mesh-less serving still passes an explicit sharding:
            # without one Orbax falls back to the checkpoint's sharding
            # file — unsafe when restoring on a different topology than
            # saved (the managed-jobs recovery shape) and noisy.
            single = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            abs_tree = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=single),
                abstract)
        try:
            restored = ckpt_lib.load_params_for_serving(
                manager, abs_tree, step=latest)
        except ValueError as e:
            if self.quantize:
                # Quantized serving uses the unscanned layout, but the
                # trainer saves scanned ('layers' stacked) trees by
                # default: restore scanned, then unstack.
                scanned = self._try_load_scanned(ckpt_lib, manager,
                                                 latest)
                if scanned is not None:
                    return scanned
            # Genuine tree/shape mismatch; other failures (network,
            # auth, corruption) propagate with their own tracebacks.
            hint = ''
            if any('pos_embed' in '/'.join(map(str, path))
                   for path, _ in jax.tree_util.tree_flatten_with_path(
                       abs_tree)[0]):
                hint = (' (this family sizes pos_embed by max_seq_len; '
                        'serve with the same max_seq_len the model was '
                        'trained with)')
            raise ValueError(
                f'checkpoint param tree does not match model '
                f'{self.config.name!r}: {e}{hint}') from e
        logger.info(f'loaded checkpoint step {latest} from {directory}')
        return restored

    def _try_load_scanned(self, ckpt_lib, manager, latest):
        """Restore a scanned-layout checkpoint and unstack it into the
        unscanned layout; None if the scanned shape doesn't fit
        either."""
        scanned_model, _ = models_lib.get_model(
            self._model_name,
            **{**self._overrides, 'scan_layers': True})
        rng = jax.random.PRNGKey(0)
        abstract = jax.eval_shape(lambda: scanned_model.init(
            rng, jnp.zeros((1, 1), jnp.int32)))['params']
        single = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        abs_tree = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=single),
            sharding_lib.unbox(abstract))
        try:
            restored = ckpt_lib.load_params_for_serving(
                manager, abs_tree, step=latest)
        except ValueError:
            return None
        logger.info('loaded scanned checkpoint; unstacking layers for '
                    'quantized (unscanned) serving.')
        return unstack_scanned_params(restored, self.config.n_layers)

    def _fresh_cache(self):
        def _make(leaf, sharding=None):
            if sharding is not None:
                return jnp.zeros(leaf.shape, leaf.dtype,
                                 device=sharding)
            return jnp.zeros(leaf.shape, leaf.dtype)
        if self._cache_shardings is None:
            return jax.tree.map(_make, self._abstract_cache)
        return jax.tree.map(_make, self._abstract_cache,
                            self._cache_shardings)

    def _bucketed(self, s_max: int) -> int:
        b = self.prefill_bucket
        padded = ((s_max + b - 1) // b) * b
        return min(padded, self.max_seq_len)

    def cache_read_bytes_per_step(self, context: Optional[int] = None,
                                  row_contexts: Optional[Sequence[int]]
                                  = None,
                                  decode_kernel: str = 'xla'
                                  ) -> Dict[str, float]:
        """Estimated HBM bytes one decode step reads from THIS engine's
        cache (grouped epilogue vs the old repeat path) — see
        decode_cache_read_bytes.  Paged engines charge per-row
        allocated pages: pass `row_contexts` for live per-slot context
        lengths; without it every slot is assumed at `context` (or
        max_seq_len), the paged worst case.  `decode_kernel` selects
        the paged epilogue model: 'xla' charges the gather_pages
        round-trip, 'fused' reports epilogue_bytes == 0."""
        if self.page_size:
            if row_contexts is None:
                ctx = context if context is not None \
                    else self.max_seq_len
                row_contexts = [ctx] * self.max_batch
            return decode_cache_read_bytes(
                self._abstract_cache, self.config.n_heads, context,
                page_size=self.page_size, row_contexts=row_contexts,
                decode_kernel=decode_kernel)
        return decode_cache_read_bytes(self._abstract_cache,
                                       self.config.n_heads, context)

    def sharding_info(self) -> Dict[str, Any]:
        """`sharding` block for /health?verbose=1: mesh geometry plus
        how the KV pool actually sharded — `pool_mode` is the
        paged_pool_mode ladder outcome, `fallback` flags the non-fast
        paths (page-/sequence-sharded or replicated pools, i.e.
        anything but the kv-head split the fused kernel lowers)."""
        mesh = self.mesh
        tensor = max(mesh.shape.get('tensor', 1), 1) \
            if mesh is not None else 1
        mode = paged_pool_mode(tensor, self.pool_kvh,
                               self.n_pages if self.page_size else 0,
                               self.page_size)
        return dict(
            mesh_devices=(mesh.devices.size if mesh is not None
                          else 1),
            axes=({a: int(s) for a, s in mesh.shape.items() if s > 1}
                  if mesh is not None else {}),
            pool_mode=mode,
            pool_kvh=self.pool_kvh,
            kvh_per_shard=(self.pool_kvh // tensor
                           if mode == 'kv_heads' else self.pool_kvh),
            fallback=mode in ('pages', 'sequence', 'replicated'),
        )

    # -- generation --------------------------------------------------------
    def publish_memory_watermarks(self) -> None:
        """Scrape-time device-memory watermark; see the continuous
        engine's twin."""
        _publish_device_memory_peak(self._met)

    def generate(self, prompts: Sequence[Sequence[int]],
                 sampling: Optional[SamplingConfig] = None,
                 http_request_id: Optional[str] = None,
                 trace_parent: Optional[str] = None
                 ) -> List[List[int]]:
        """Generate continuations for up to `max_batch_size` prompts of
        (possibly) different lengths. Returns one id list per prompt.
        `http_request_id`/`trace_parent` stamp the external request id
        on every trace this batch begins (whole-batch serving runs one
        HTTP request per batch)."""
        if self.page_size:
            # The paged layout only exists on the slot-mode trace; the
            # request-level whole-batch path has no allocator.
            raise RuntimeError(
                'paged KV cache (page_size > 0) requires slot-mode '
                'serving — use ContinuousBatchingEngine')
        cfg = sampling or SamplingConfig()
        n = len(prompts)
        if n == 0:
            return []
        if n > self.max_batch:
            raise ValueError(
                f'{n} prompts > max_batch_size={self.max_batch}.')
        lengths = np.array([len(p) for p in prompts], np.int32)
        if (lengths <= 0).any():
            raise ValueError('empty prompt')
        if int(lengths.max()) + cfg.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f'prompt ({int(lengths.max())}) + max_new_tokens '
                f'({cfg.max_new_tokens}) exceeds max_seq_len '
                f'{self.max_seq_len}.')
        # Bucket the padded prompt length so prefill compiles once per
        # bucket, not once per (prompt length, max_new_tokens) pair;
        # only near the max_seq_len ceiling does the clamp reintroduce
        # a max_new dependence.
        lmax = int(lengths.max())
        s_max = min(self._bucketed(lmax),
                    self.max_seq_len - cfg.max_new_tokens)
        s_max = max(s_max, lmax)

        b = self.max_batch
        tokens = np.zeros((b, s_max), np.int32)
        prompt_mask = np.zeros((b, s_max), bool)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
            prompt_mask[i, :len(p)] = True
        full_lengths = np.zeros((b,), np.int32)
        full_lengths[:n] = lengths

        kv_mask = jnp.zeros((b, self.max_seq_len), bool)
        kv_mask = kv_mask.at[:, :s_max].set(jnp.asarray(prompt_mask))
        positions = jnp.broadcast_to(
            jnp.arange(s_max, dtype=jnp.int32)[None], (b, s_max))
        lengths_dev = jnp.asarray(full_lengths)

        cache = self._fresh_cache()
        self._generation += 1
        met = self._met
        rids = [f'gen{self._generation}-{i}' for i in range(n)]
        for i, rid in enumerate(rids):
            trace = self.traces.begin(rid,
                                      prompt_tokens=int(lengths[i]),
                                      http_request_id=http_request_id)
            trace.trace_parent = trace_parent
            # Whole-batch generate admits and prefills immediately.
            self.traces.event(rid, 'admitted')
        met.submitted.inc(n)
        met.prompt_tokens.inc(int(lengths.sum()))
        met.inflight.set(self.traces.inflight_count)
        step_read_bytes = self._read_bytes_per_pos * self.max_seq_len
        if cfg.seed is not None:
            rng = jax.random.PRNGKey(int(cfg.seed) & 0x7FFFFFFF)
        else:
            rng = jax.random.fold_in(self._rng, self._generation)
        ctx = self.mesh if self.mesh is not None \
            else contextlib.nullcontext()
        with ctx:
            logits, cache = self._prefill(
                self.params, cache, jnp.asarray(tokens), positions,
                kv_mask)
            last = logits[jnp.arange(b),
                          jnp.maximum(lengths_dev - 1, 0)]
            for rid in rids:
                self.traces.event(rid, 'prefill_chunk')
                self.traces.event(rid, 'prefill_done')

            outputs: List[List[int]] = [[] for _ in range(n)]
            done = np.zeros((b,), bool)
            done[n:] = True
            first_step: List[Optional[int]] = [None] * n
            last_step: List[Optional[int]] = [None] * n
            for t in range(cfg.max_new_tokens):
                t_dispatch = time.perf_counter()
                tok_dev, last, cache, kv_mask = self._decode(
                    self.params, cache, last, kv_mask, lengths_dev,
                    # skylint: disable=key-reuse (root key; _decode_step fold_ins per-step)
                    jnp.int32(s_max), jnp.int32(t), rng,
                    jnp.asarray(~done), temperature=cfg.temperature,
                    top_k=cfg.top_k, top_p=cfg.top_p)
                next_tok = np.asarray(jax.device_get(tok_dev))
                t_join = time.perf_counter()
                self._step_idx += 1
                step_idx = self._step_idx
                live = 0
                ctx_sum = 0
                for i in range(n):
                    if not done[i]:
                        live += 1
                        # Attention this step spans the prompt plus
                        # everything decoded so far plus this token.
                        ctx_sum += int(lengths[i]) + t + 1
                        if first_step[i] is None:
                            first_step[i] = step_idx
                        last_step[i] = step_idx
                        outputs[i].append(int(next_tok[i]))
                        if len(outputs[i]) == 1:
                            self.traces.event(rids[i], 'first_token')
                        if cfg.eos_id is not None and \
                                int(next_tok[i]) == cfg.eos_id:
                            done[i] = True
                met.steps.inc()
                met.slot_steps.inc(live)
                met.output_tokens.inc(live)
                met.live_slots.set(live)
                met.occupancy.set(live / self.max_batch)
                met.read_bytes.observe(step_read_bytes)
                led = self.step_ledger
                if led.enabled:
                    # Whole-batch generate has no dispatch/consume
                    # split: the step's wall time is dispatch->join.
                    rec = led.record(
                        step=step_idx, mode='plain',
                        t_enter=t_dispatch, t_dispatch=t_dispatch,
                        t_join=t_join, t_commit=time.perf_counter(),
                        rows=live, tokens=live, ctx_sum=ctx_sum,
                        read_bytes=step_read_bytes)
                    if rec is not None:
                        met.step_mfu.set(rec['mfu'])
                        met.model_flops_per_token.set(
                            rec['flops_per_token'])
                if done.all():
                    break
        for i, rid in enumerate(rids):
            trace = self.traces.finish(rid, 'finished',
                                       output_tokens=len(outputs[i]),
                                       first_step_idx=first_step[i],
                                       last_step_idx=last_step[i])
            met.finished.inc()
            met.observe_finished(trace)
        met.live_slots.set(0)
        met.occupancy.set(0.0)
        met.inflight.set(self.traces.inflight_count)
        return outputs
