"""KV-handoff artifact: the wire format for disaggregated serving,
fleet prefix-cache transfer, and live slot migration.

A PREFILL-role replica runs a prompt's chunked prefill at full batch
width, then hands the request to a DECODE-role replica as this
artifact instead of keeping the slot: the prompt's KV prefix (int8
pages ship with their sibling scale rows; bf16 ships as-is), the
logits row at the prompt's last true token, and the complete sampler
state — including the seed token already sampled from the prefill
logits with the same (seed, 0) key fold the fused decode step would
use, so the receiver's decode stream is bit-identical to a single
`--role both` replica's.

Page ids, not tensors, do the deduplication: the receiver looks the
prompt up in its own chain-hash prefix map (`infer/paging.py`) and
every page it already holds is admitted by reference — the paged
insert redirects those columns to the reserved null page instead of
rewriting a refcounted page.  Only the contiguous `[.., :true_len, ..]`
slice of the batch-1 prefill cache crosses the wire; the padded tail
is masked forever on both sides and never ships.

Version 2 generalizes the format along two axes:

- ``kind`` (header field, default ``'prefill'``) names what the
  artifact carries.  ``'slot'`` is a LIVE mid-generation decode slot
  checkpointed for migration: the shipped KV covers ``kv_len``
  positions (prompt + garbage pad gap + generated tokens) and the
  header adds the full decode cursor/sampler restart state
  (``generated``, ``outputs``, ``steps``, ``pending_form``).
  ``'kv_prefix'`` is a fleet prefix-cache transfer: spilled host-RAM
  pool pages keyed by their chain hashes, no sampler state at all.
- an optional zlib-compressed tensor section (stdlib-only): the
  header's ``compressed: 'zlib'`` + ``raw_nbytes`` announce it, and
  the tensor directory's offsets index the DECOMPRESSED payload.

Wire layout (versioned; `HandoffVersionError` on mismatch so a mixed
fleet mid-rollout fails closed — v1 readers reject v2 artifacts and
vice versa, both as HTTP 409):

    magic 'SKHO' | u16 version | u32 header_len | header JSON | tensors

The header carries the model/cache geometry (checked by the receiver
before any allocation), resolved sampling state, prompt token ids
(the dedupe + prefix-registration key), and a tensor directory of
``{name, dtype, shape, offset, nbytes}`` entries into the raw
little-endian tensor payload that follows.

Deliberately engine-agnostic: numpy + stdlib only (ml_dtypes supplies
the bfloat16 wire dtype; it ships with jax), no jax import, so the
router and tests can load it without touching a device runtime.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Wire identity and header names live in skypilot_tpu/protocol.py —
# the single source for the fleet's cross-process surface — and are
# re-exported here under their historical names.  protocol is stdlib
# only, so this module stays loadable without a device runtime.
from skypilot_tpu.protocol import (
    DECODE_TARGET_HEADER,
    PREFIX_PEER_HEADER,
    SKHO_MAGIC as MAGIC,
    SKHO_VERSION as VERSION,
)

_PREAMBLE = struct.Struct('>4sHI')

# What the artifact carries (header `kind`; absent == 'prefill' so v2
# prefill artifacts stay self-describing).
KIND_PREFILL = 'prefill'
KIND_SLOT = 'slot'
KIND_KV_PREFIX = 'kv_prefix'
KINDS = (KIND_PREFILL, KIND_SLOT, KIND_KV_PREFIX)

# Batch-1 prefill-cache leaves that ship: K/V plus the sibling scale
# rows of the int8 cache mode.  Names match models/llama.py's cache
# collection; the cursor scalars never ship (the receiver rebuilds
# them from true_len).
KV_LEAF_NAMES = ('cached_key', 'cached_value',
                 'cached_key_scale', 'cached_value_scale')

# The logits row at the prompt's last true token: seeds the receiver's
# first decode draw (or the verify step's re-derivation of it).
LAST_ROW = 'last_row'

_REQUIRED_META = ('model', 'kv_cache_dtype', 'page_size',
                  'max_seq_len', 'true_len', 'pad', 'prompt_ids',
                  'seed', 'seed_token', 'sampling')
_REQUIRED_SAMPLING = ('max_new_tokens', 'temperature', 'top_k',
                      'top_p', 'eos_id')
# kind='slot' additions: the decode restart state.  kv_len is the
# shipped KV extent (pad + generated, minus one in pending form —
# speculating engines hold the pending token's KV OUT of cache);
# pending_form says which convention the sender used, and the
# receiver refuses a form its own stepping mode cannot resume.
_REQUIRED_SLOT = ('kv_len', 'generated', 'outputs', 'steps',
                  'pending_form')
# kind='kv_prefix' carries no sampler state: just enough geometry for
# the receiver to trust the pages, plus the chain hashes keying them.
_REQUIRED_KV_PREFIX = ('model', 'kv_cache_dtype', 'page_size',
                       'hashes')


class HandoffError(ValueError):
    """Base class: anything wrong with a handoff artifact."""


class HandoffFormatError(HandoffError):
    """Malformed or geometry-incompatible artifact (HTTP 400/409)."""


class HandoffVersionError(HandoffError):
    """Artifact from a different wire-format version (HTTP 409)."""


def _dtype_from_name(name: str) -> np.dtype:
    """Wire dtype name -> numpy dtype; bfloat16 et al. resolve through
    ml_dtypes (a jax dependency, so always importable next to an
    engine; a stdlib-only consumer without it can still read int8/f32
    artifacts)."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError) as e:
        raise HandoffFormatError(
            f'unknown tensor dtype {name!r} in handoff artifact') from e


def _required_fields(kind: str) -> Tuple[str, ...]:
    if kind == KIND_KV_PREFIX:
        return _REQUIRED_KV_PREFIX
    if kind == KIND_SLOT:
        return _REQUIRED_META + _REQUIRED_SLOT
    return _REQUIRED_META


def serialize_artifact(meta: Dict[str, Any],
                       tensors: Dict[str, np.ndarray],
                       compress: bool = False) -> bytes:
    """Render one handoff artifact.  `meta` must carry the required
    fields for its `kind` (absent kind == 'prefill'); `tensors` maps
    leaf names (cache pytree path joined with '/', plus 'last_row') to
    host arrays.  Iteration order of `tensors` is the payload order.
    With `compress`, the tensor payload ships zlib-deflated and the
    header announces it (v1 readers never see this far — the version
    check fails closed first)."""
    kind = meta.get('kind', KIND_PREFILL)
    if kind not in KINDS:
        raise HandoffFormatError(f'unknown artifact kind {kind!r}')
    for key in _required_fields(kind):
        if key not in meta:
            raise HandoffFormatError(
                f'handoff meta missing required field {key!r}')
    header = dict(meta)
    directory: List[Dict[str, Any]] = []
    chunks: List[bytes] = []
    offset = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        directory.append({
            'name': name,
            'dtype': np.dtype(arr.dtype).name,
            'shape': list(arr.shape),
            'offset': offset,
            'nbytes': len(raw),
        })
        chunks.append(raw)
        offset += len(raw)
    header['tensors'] = directory
    payload = b''.join(chunks)
    if compress:
        header['compressed'] = 'zlib'
        header['raw_nbytes'] = offset
        payload = zlib.compress(payload)
    header_raw = json.dumps(header, separators=(',', ':')).encode()
    return b''.join([_PREAMBLE.pack(MAGIC, VERSION, len(header_raw)),
                     header_raw, payload])


def raw_payload_nbytes(meta: Dict[str, Any]) -> int:
    """Uncompressed tensor-payload size of a (de)serialized artifact's
    header — the `raw_nbytes` announcement when compressed, else the
    directory sum.  Feeds the compressed-vs-raw bytes metrics/bench
    reporting without a second serialization pass."""
    if 'raw_nbytes' in meta:
        return int(meta['raw_nbytes'])
    return sum(int(e.get('nbytes', 0))
               for e in meta.get('tensors', ()) or ())


def deserialize_artifact(blob: bytes
                         ) -> Tuple[Dict[str, Any],
                                    Dict[str, np.ndarray]]:
    """Parse one artifact -> (meta, {name: array}).  Arrays are
    zero-copy views into `blob` (read-only; into the decompressed
    buffer for a zlib artifact); callers that mutate must copy.
    Raises HandoffVersionError on a version mismatch and
    HandoffFormatError on anything malformed — both BEFORE any
    allocation-sized work, so a hostile or stale artifact costs the
    receiver one header parse."""
    if len(blob) < _PREAMBLE.size:
        raise HandoffFormatError('handoff artifact truncated (preamble)')
    magic, version, header_len = _PREAMBLE.unpack_from(blob, 0)
    if magic != MAGIC:
        raise HandoffFormatError(
            f'bad handoff magic {magic!r} (not a handoff artifact)')
    if version != VERSION:
        raise HandoffVersionError(
            f'handoff artifact version {version} != supported '
            f'{VERSION}; sender and receiver replicas must run the '
            f'same wire format')
    body = _PREAMBLE.size
    if len(blob) < body + header_len:
        raise HandoffFormatError('handoff artifact truncated (header)')
    try:
        meta = json.loads(blob[body:body + header_len])
    except ValueError as e:
        raise HandoffFormatError(
            f'handoff header is not valid JSON: {e}') from e
    if not isinstance(meta, dict):
        raise HandoffFormatError('handoff header must be a JSON object')
    kind = meta.get('kind', KIND_PREFILL)
    if kind not in KINDS:
        raise HandoffFormatError(f'unknown artifact kind {kind!r}')
    for key in _required_fields(kind):
        if key not in meta:
            raise HandoffFormatError(
                f'handoff header missing required field {key!r}')
    if kind != KIND_KV_PREFIX:
        sampling = meta['sampling']
        if not isinstance(sampling, dict):
            raise HandoffFormatError(
                'handoff sampling must be an object')
        for key in _REQUIRED_SAMPLING:
            if key not in sampling:
                raise HandoffFormatError(
                    f'handoff sampling missing required field {key!r}')
    directory = meta.get('tensors')
    if not isinstance(directory, list):
        raise HandoffFormatError('handoff header missing tensor '
                                 'directory')
    payload = body + header_len
    compressed = meta.get('compressed')
    if compressed is None:
        buf: Any = blob
        base = payload
        limit = len(blob)
    elif compressed == 'zlib':
        try:
            buf = zlib.decompress(blob[payload:])
        except zlib.error as e:
            raise HandoffFormatError(
                f'handoff tensor payload does not inflate: {e}') from e
        try:
            want = int(meta['raw_nbytes'])
        except (KeyError, TypeError, ValueError) as e:
            raise HandoffFormatError(
                'compressed handoff header missing raw_nbytes') from e
        if len(buf) != want:
            raise HandoffFormatError(
                f'handoff payload inflated to {len(buf)} bytes, '
                f'header announced {want}')
        base = 0
        limit = len(buf)
    else:
        raise HandoffFormatError(
            f'unknown handoff compression {compressed!r}')
    tensors: Dict[str, np.ndarray] = {}
    for entry in directory:
        try:
            name = entry['name']
            dtype = _dtype_from_name(entry['dtype'])
            shape = tuple(int(d) for d in entry['shape'])
            offset = int(entry['offset'])
            nbytes = int(entry['nbytes'])
        except (TypeError, KeyError, ValueError) as e:
            raise HandoffFormatError(
                f'bad tensor directory entry {entry!r}') from e
        expected = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if nbytes != expected:
            raise HandoffFormatError(
                f'tensor {name!r}: nbytes {nbytes} != shape/dtype '
                f'size {expected}')
        start = base + offset
        if offset < 0 or start + nbytes > limit:
            raise HandoffFormatError(
                f'tensor {name!r} extends past the artifact payload')
        tensors[name] = np.frombuffer(
            buf, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
            offset=start).reshape(shape)
    return meta, tensors


def prompt_page_split(prompt_ids: Sequence[int], shared_pages: int,
                      page_size: int) -> Tuple[int, int]:
    """(shipped, deduped) prompt-page counts for the handoff metrics:
    pages covering the true prompt that had to arrive over the wire vs
    pages the receiver already held via its chain-hash prefix map.
    Decode-headroom pages are excluded — nothing ships for them."""
    if page_size <= 0:
        return 0, 0
    prompt_pages = -(-len(prompt_ids) // page_size)
    deduped = min(max(int(shared_pages), 0), prompt_pages)
    return prompt_pages - deduped, deduped


def serialize_kv_prefix(model: str, kv_cache_dtype: str,
                        page_size: int, hashes: Sequence[int],
                        pages: Sequence[Dict[str, np.ndarray]],
                        compress: bool = False) -> bytes:
    """Render a fleet prefix-cache transfer: `pages[i]` maps pool-leaf
    names to that page's host arrays, keyed by chain hash
    `hashes[i]`.  Tensor names are ``<leaf>/<i>`` so heterogeneous
    per-leaf shapes (scanned vs unscanned pools) ship unmodified."""
    if len(hashes) != len(pages):
        raise HandoffFormatError(
            f'{len(hashes)} hashes != {len(pages)} pages')
    meta = {
        'kind': KIND_KV_PREFIX,
        'model': model,
        'kv_cache_dtype': kv_cache_dtype,
        'page_size': page_size,
        'hashes': [int(h) for h in hashes],
    }
    tensors: Dict[str, np.ndarray] = {}
    for i, leaves in enumerate(pages):
        for name, arr in leaves.items():
            tensors[f'{name}/{i}'] = arr
    return serialize_artifact(meta, tensors, compress=compress)


def split_kv_prefix(meta: Dict[str, Any],
                    tensors: Dict[str, np.ndarray]
                    ) -> List[Tuple[int, Dict[str, np.ndarray]]]:
    """Invert serialize_kv_prefix on a deserialized artifact:
    [(chain_hash, {leaf: array}), ...] in shipped order."""
    hashes = meta.get('hashes') or []
    pages: List[Dict[str, np.ndarray]] = [dict() for _ in hashes]
    for key, arr in tensors.items():
        name, _, idx = key.rpartition('/')
        try:
            i = int(idx)
        except ValueError as e:
            raise HandoffFormatError(
                f'kv_prefix tensor {key!r} has no page index') from e
        if not name or not 0 <= i < len(pages):
            raise HandoffFormatError(
                f'kv_prefix tensor {key!r} out of range')
        pages[i][name] = arr
    return [(int(h), leaves) for h, leaves in zip(hashes, pages)]
