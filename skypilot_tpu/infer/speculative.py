"""Speculative decoding: draft proposals + parity-guarded acceptance.

Decode latency is bounded by the NUMBER of target-model steps per
token (PR 7's paged cache already minimized the bytes per step).  This
module lets one target forward commit several tokens:

  1. a proposer guesses k tokens — either a small DRAFT MODEL decoding
     greedily against its own private KV cache, or, with zero extra
     weights, SELF-DRAFTING via prompt-lookup (n-gram) matching;
  2. the target scores all k+1 positions (the pending token plus the k
     proposals) in ONE multi-token slot forward (models/llama.py
     `_verify_positions`) over its paged/contiguous cache;
  3. the acceptance kernel keeps the longest draft prefix the target
     agrees with and samples one extra token, so every verify commits
     between 1 and k+1 tokens.

Acceptance is parity-guarded:

  * temperature == 0 — a proposal is accepted iff it IS the target's
    argmax at that position, and the correction/bonus token is the
    argmax after the accepted prefix: the committed stream is
    bit-identical to plain greedy decode.
  * temperature > 0 — standard rejection sampling against the target's
    FILTERED distribution p (the exact softmax plain decode draws
    from, engine.filter_logits_rows): accept d with probability p(d)
    (proposals are point-mass), on rejection resample from the
    leftover distribution (p with d removed, renormalized).  The
    marginal of every committed token is exactly p — the output
    distribution is provably unchanged.

Rollback never copies tensors: rejected proposals' K/V was written to
cache positions that acceptance simply does not reveal, so the next
verify overwrites them in place (the paged cache's block tables are
untouched — "rollback via block-table truncation" falls out of the
mask being the only source of truth for what a row has committed).

Async-pipeline sequencing (engine.py's double-buffered decode loop):
a speculative dispatch reads HOST state — each row's pending token
(``slot.outputs[-1]``) and commit count — that only exists after the
previous verify was consumed, so the engine always JOINS the in-flight
verify before proposing the next window; the one-step lookahead
overlaps the in-flight verify with admission/prefill host work, never
with a dependent propose.  Everything device-side needs no such
fence: ``DraftRunner.commit`` reveals the accepted window in the
draft's kv_mask from on-device ``counts`` (no host fetch), and a
rejected window is squashed by the verify step's own mask arithmetic,
so abandoning an in-flight verify (recover()/abort()) rolls back
draft and target together for free — both caches are rebuilt, there
is no host-side speculation state to unwind.
"""
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.observability import metrics as metrics_lib


# -- self-drafting: prompt-lookup / n-gram proposals --------------------

def ngram_propose(context: Sequence[int], k: int, max_ngram: int = 3,
                  min_ngram: int = 1) -> List[int]:
    """Prompt-lookup proposals (zero extra weights): find the most
    recent earlier occurrence of the longest suffix n-gram of
    `context` and propose the tokens that followed it, up to k.
    Returns [] when nothing matches — the engine then verifies only
    the pending token (a plain decode step's worth of progress).
    Ideal for the shared-prefix / templated traffic the prefix cache
    already serves: continuations of repeated spans are free tokens.
    """
    n_ctx = len(context)
    if k <= 0 or n_ctx < min_ngram + 1:
        return []
    for n in range(min(max_ngram, n_ctx - 1), min_ngram - 1, -1):
        suffix = tuple(context[n_ctx - n:])
        # Most recent earlier occurrence wins: recency tracks local
        # repetition (code, templates) better than first match.
        for start in range(n_ctx - n - 1, -1, -1):
            if tuple(context[start:start + n]) == suffix:
                cont = context[start + n:start + n + k]
                if cont:
                    return list(cont)
                break
    return []


# -- acceptance kernel --------------------------------------------------

def accept_draft_rows(logits: jax.Array, drafts: jax.Array,
                      n_prop: jax.Array, seeds: jax.Array,
                      gens: jax.Array, temps: jax.Array,
                      top_ks: jax.Array, top_ps: jax.Array, *,
                      max_k: int, use_top_p: bool,
                      top_p_in_topk: bool = False):
    """Accept/resample one verify forward's proposals.

    logits: [B, k+1, V] — verify logits; row j is the target's
        distribution for the position AFTER the j-th fed token, so
        logits[:, i-1] judges drafts[:, i-1] (the i-th proposal) and
        logits[:, n] seeds the correction/bonus token after an
        n-long accepted prefix.
    drafts: [B, k] int32 proposals; n_prop: [B] per-row valid count
        (<= k; positions past it are auto-rejected padding).
    seeds/gens: per-row PRNG basis — keys fold (seed, generated, i) so
        draws are reproducible regardless of batch composition.
    temps/top_ks/top_ps + static max_k/use_top_p/top_p_in_topk: the
        same per-row sampling surface as engine.sample_logits_rows.

    Returns (out_tokens [B, k+1], counts [B]): out_tokens[b, :counts[b]]
    are the committed tokens — the accepted draft prefix plus exactly
    one sampled token (the leftover resample at the first rejection,
    or the bonus token when everything was accepted).
    """
    b, s, v = logits.shape
    k = s - 1
    greedy_ok = drafts == jnp.argmax(logits[:, :k], axis=-1)  # [B, k]
    # Filtered target distributions for every judged position, via the
    # SAME kernel plain decode samples from: flatten [B, k] positions
    # into rows, repeat each row's sampling config across positions.
    flat = logits[:, :k].reshape(b * k, v)
    filt = engine_lib.filter_logits_rows(
        flat, jnp.repeat(temps, k), jnp.repeat(top_ks, k),
        jnp.repeat(top_ps, k), max_k=max_k, use_top_p=use_top_p,
        top_p_in_topk=top_p_in_topk)
    probs = jax.nn.softmax(filt, axis=-1).reshape(b, k, v)
    p_draft = jnp.take_along_axis(
        probs, drafts[:, :, None], axis=-1)[..., 0]           # [B, k]
    base_keys = jax.vmap(
        lambda sd, g: jax.random.fold_in(
            jax.random.PRNGKey(sd), g))(seeds, gens)
    accept_keys = jax.vmap(
        lambda kb: jax.vmap(
            lambda i: jax.random.fold_in(kb, i + 1))(
                jnp.arange(k)))(base_keys)                    # [B, k]
    u = jax.vmap(jax.vmap(
        lambda key: jax.random.uniform(key)))(accept_keys)    # [B, k]
    stoch_ok = u < p_draft
    ok = jnp.where(temps[:, None] > 0, stoch_ok, greedy_ok)
    ok = ok & (jnp.arange(k)[None, :] < n_prop[:, None])
    prefix = jnp.cumprod(ok.astype(jnp.int32), axis=-1)
    n_acc = jnp.sum(prefix, axis=-1).astype(jnp.int32)        # [B]
    # Correction/bonus token from the distribution after the accepted
    # prefix.  Stochastic rows that REJECTED a proposal resample from
    # the leftover distribution: the filtered target with the rejected
    # token removed and renormalized (point-mass proposals make the
    # general max(p-q, 0) residual collapse to exactly this).  Greedy
    # rows need no exclusion — a greedy mismatch already means the
    # argmax differs from the rejected proposal.
    all_filt = engine_lib.filter_logits_rows(
        logits.reshape(b * s, v), jnp.repeat(temps, s),
        jnp.repeat(top_ks, s), jnp.repeat(top_ps, s), max_k=max_k,
        use_top_p=use_top_p,
        top_p_in_topk=top_p_in_topk).reshape(b, s, v)
    final_filt = jnp.take_along_axis(
        all_filt, n_acc[:, None, None], axis=1)[:, 0]         # [B, V]
    final_raw = jnp.take_along_axis(
        logits, n_acc[:, None, None], axis=1)[:, 0]
    rejected = jnp.take_along_axis(
        drafts, jnp.minimum(n_acc, k - 1)[:, None], axis=1)[:, 0]
    exclude = (temps > 0) & (n_acc < n_prop)
    final_filt = jnp.where(
        exclude[:, None] & (jnp.arange(v)[None, :]
                            == rejected[:, None]),
        -1e30, final_filt)
    final_keys = jax.vmap(
        lambda kb: jax.random.fold_in(kb, 0))(base_keys)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(
            final_keys, final_filt).astype(jnp.int32)
    t_new = jnp.where(temps > 0, sampled,
                      jnp.argmax(final_raw, axis=-1).astype(jnp.int32))
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)       # [B, k+1]
    pos_idx = jnp.arange(k + 1)[None, :]
    out = jnp.where(pos_idx == n_acc[:, None], t_new[:, None],
                    drafts_pad)
    out = jnp.where(pos_idx <= n_acc[:, None], out, 0)
    return out, n_acc + 1


# -- spec observability --------------------------------------------------

def spec_metrics(registry: metrics_lib.Registry) -> Dict[str, Any]:
    """Register the skytpu_spec_* series (names single-sourced through
    observability.METRIC_CONTRACT).  Registered only on engines with
    speculation enabled — the replica-side scrape contract test filters
    the prefix out for plain servers."""
    return dict(
        steps=registry.counter(
            'skytpu_spec_steps_total',
            'Speculative verify steps run (one multi-token target '
            'forward each).'),
        draft_steps=registry.counter(
            'skytpu_spec_draft_steps_total',
            'Draft-model decode forwards run (k+1 per verify step in '
            'draft mode; 0 when self-drafting).'),
        proposed=registry.counter(
            'skytpu_spec_proposed_tokens_total',
            'Draft tokens proposed for verification.'),
        accepted=registry.counter(
            'skytpu_spec_accepted_tokens_total',
            'Proposed tokens the target accepted.'),
        accepted_len=registry.histogram(
            'skytpu_spec_accepted_tokens',
            'Tokens committed per sequence per verify step (accepted '
            'prefix + the resampled/bonus token): 1 = nothing '
            'accepted, k+1 = full acceptance.',
            buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0)),
    )


# -- draft-model runner --------------------------------------------------

class DraftRunner:
    """Draft-model proposer mirroring the target engine's slot layout.

    Same n_slots / max_seq_len / pad cursors as the target, so target
    cursors map 1:1 onto the draft cache.  When the target is paged
    the draft rides its OWN smaller pool (draft-sized pages): sized at
    full coverage (n_slots * pages_per_slot + 1) with no prefix
    sharing or oversubscription, so no allocator is needed — slot i
    owns the fixed page range [1 + i*pps, 1 + (i+1)*pps) forever and
    rollback is kv-mask truncation exactly like the target.

    Per verify iteration the draft runs k+1 sequential greedy decode
    steps under one lax.scan: steps 1..k emit the proposals d_1..d_k;
    the extra step feeds d_k back so its K/V lands in the draft cache
    (full acceptance would otherwise leave a hole the next iteration's
    context misses).  `commit()` then reveals only the committed
    window, discarding the scan's speculative reveals.
    """

    def __init__(self, model: str, *, target_vocab_size: int,
                 n_slots: int, max_seq_len: int, spec_k: int,
                 mesh=None, checkpoint_dir: Optional[str] = None,
                 model_overrides: Optional[Dict[str, Any]] = None,
                 param_dtype: Any = jnp.bfloat16,
                 prefill_bucket: int = 64,
                 quantize: Optional[str] = None,
                 kv_cache_dtype: str = 'auto',
                 page_size: int = 0, seed: int = 0) -> None:
        if spec_k <= 0:
            raise ValueError(f'spec_k must be positive, got {spec_k}')
        self.k = spec_k
        self._eng = engine_lib.InferenceEngine(
            model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
            max_batch_size=n_slots, max_seq_len=max_seq_len,
            model_overrides=model_overrides, param_dtype=param_dtype,
            prefill_bucket=prefill_bucket, quantize=quantize,
            kv_cache_dtype=kv_cache_dtype, page_size=page_size,
            max_pages=0, seed=seed)
        # Tokenizer-family guard: draft proposals are TARGET token ids
        # — a draft trained on a different vocabulary would silently
        # decode garbage (every proposal rejected at best, nonsense
        # committed at worst).  Vocab size is the strongest signal the
        # configs carry; fail loudly at init, not mid-request.
        if self._eng.config.vocab_size != target_vocab_size:
            raise ValueError(
                f'draft model {model!r} has vocab_size='
                f'{self._eng.config.vocab_size} but the target expects '
                f'{target_vocab_size}: speculative decoding requires '
                f'the SAME tokenizer family for draft and target '
                f'(proposals are exchanged as token ids).')
        self.model_name = model
        self.loaded_real_weights = self._eng.loaded_real_weights
        self.n_slots = n_slots
        self.max_seq_len = self._eng.max_seq_len
        self.page_size = self._eng.page_size
        model_obj = self._eng.model

        rng = jax.random.PRNGKey(seed)
        abstract1 = jax.eval_shape(
            lambda: model_obj.init(rng, jnp.zeros((1, 1), jnp.int32)))
        from skypilot_tpu.parallel import sharding as sharding_lib
        self._abstract_cache1 = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            sharding_lib.unbox(abstract1['cache']))

        def _forward(p, cache, tokens, positions, kv_mask):
            p = engine_lib.maybe_dequantize_params(
                p, self._eng.config.param_dtype)
            logits, mutated = model_obj.apply(
                {'params': p, 'cache': cache}, tokens, positions,
                kv_mask, mutable=['cache'])
            return logits, mutated['cache']

        def _prefill_fwd(p, cache, tokens, positions, kv_mask):
            return _forward(p, cache, tokens, positions, kv_mask)

        self._prefill1 = jax.jit(_prefill_fwd, donate_argnums=(1,))
        self._insert = jax.jit(engine_lib.make_insert_fn(),
                               donate_argnums=(0, 1, 2))
        if self.page_size:
            ps = self.page_size
            pps = self.max_seq_len // ps
            self._pages_per_slot = pps
            self._insert_paged = jax.jit(
                engine_lib.make_paged_insert_fn(ps, pps),
                donate_argnums=(0, 1, 2))

        def _propose(p, cache, kv_mask, t_pend, rope, cursors, active,
                     kv_bucket: int):
            """k+1 greedy draft steps under one scan (see class doc).
            kv_mask is a scan carry: each step reveals its write slot
            so the s=1 slot-mode cursor advances, but the mutated mask
            is DISCARDED by the caller — commit() re-derives reveals
            from the acceptance outcome."""
            from skypilot_tpu.models import llama as llama_lib
            brange = jnp.arange(t_pend.shape[0])

            def body(carry, j):
                cache, kv_mask, tok = carry
                reveal = kv_mask[brange, cursors + j] | active
                kv_mask = kv_mask.at[brange, cursors + j].set(reveal)
                with llama_lib.kv_read_bucket(kv_bucket):
                    logits, cache = _forward(
                        p, cache, tok[:, None], (rope + j)[:, None],
                        kv_mask)
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(
                    jnp.int32)
                return (cache, kv_mask, nxt), nxt

            (cache, kv_mask, _), outs = jax.lax.scan(
                body, (cache, kv_mask, t_pend),
                jnp.arange(self.k + 1, dtype=jnp.int32))
            # outs [k+1, B]: rows 0..k-1 are d_1..d_k; row k is the
            # cache-fill step's output, discarded.
            return jnp.transpose(outs[:self.k]), cache

        self._propose = jax.jit(_propose,
                                static_argnames=('kv_bucket',),
                                donate_argnums=(1,))

        def _commit(kv_mask, cursors, counts, active):
            slots_idx = jnp.arange(kv_mask.shape[1], dtype=jnp.int32)
            window = (active[:, None]
                      & (slots_idx[None, :] >= cursors[:, None])
                      & (slots_idx[None, :]
                         < (cursors + counts)[:, None]))
            return kv_mask | window

        self._commit = jax.jit(_commit, donate_argnums=(0,))
        self.reset()

    def reset(self) -> None:
        """Rebuild device state from zeros (engine recover() path —
        donated buffers may be invalid after a mid-step failure)."""
        self.cache = self._eng._fresh_cache()
        self.kv_mask = jnp.zeros((self.n_slots, self.max_seq_len),
                                 bool)
        self._last_dummy = jnp.zeros((self.n_slots, 1), jnp.float32)

    @property
    def params(self):
        return self._eng.params

    def admit(self, slot_idx: int, tokens: np.ndarray,
              mask_row: np.ndarray, true_len: int, pad: int) -> None:
        """Prefill the prompt into the draft's slot `slot_idx`: one
        whole-prompt batch-1 forward (the draft is small; chunking
        buys nothing) + the shared slot-insert.  `tokens`/`mask_row`
        are the target's padded prompt row and kv-mask row, so draft
        and target cursors stay aligned by construction."""
        del true_len  # alignment comes from the shared mask row
        cache1 = jax.tree.map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype),
            self._abstract_cache1)
        positions = jnp.arange(pad, dtype=jnp.int32)[None]
        _, cache1 = self._prefill1(
            self.params, cache1, jnp.asarray(tokens[:, :pad]),
            positions, jnp.asarray(mask_row)[None])
        last_row = jnp.zeros((1,), jnp.float32)   # draft keeps no last
        slot = jnp.int32(slot_idx)
        if self.page_size:
            pps = self._pages_per_slot
            table_row = jnp.arange(1 + slot_idx * pps,
                                   1 + (slot_idx + 1) * pps,
                                   dtype=jnp.int32)
            self.cache, self._last_dummy, self.kv_mask = \
                self._insert_paged(
                    self.cache, self._last_dummy, self.kv_mask,
                    cache1, last_row, jnp.asarray(mask_row),
                    table_row, slot, jnp.int32(0))
        else:
            self.cache, self._last_dummy, self.kv_mask = self._insert(
                self.cache, self._last_dummy, self.kv_mask, cache1,
                last_row, jnp.asarray(mask_row), slot)

    def propose(self, t_pend: jax.Array, rope: jax.Array,
                cursors: jax.Array, active: jax.Array,
                kv_bucket: int) -> jax.Array:
        """Draft k proposals per row; returns [B, k] device tokens
        (never synced to host — the verify consumes them on device)."""
        from skypilot_tpu.models import llama as llama_lib
        with llama_lib.slot_mode():
            drafts, self.cache = self._propose(
                self.params, self.cache, self.kv_mask, t_pend, rope,
                cursors, active, kv_bucket=kv_bucket)
        return drafts

    def commit(self, cursors: jax.Array, counts: jax.Array,
               active: jax.Array) -> None:
        """Reveal the committed window [cursor, cursor + counts) per
        active row.  Positions the scan wrote beyond it stay
        unrevealed — that is the draft-side rollback."""
        self.kv_mask = self._commit(self.kv_mask, cursors, counts,
                                    active)
