"""TPU-native inference: KV-cache engine + HTTP server.

The reference serves LLMs by shelling out to vLLM/TGI recipes
(reference `llm/qwen`, `llm/mixtral` — SURVEY.md §2.11); here serving is
first-party so SkyServe replicas run a framework-owned engine
(JetStream-style prefill/decode split) instead of an external binary.
"""
from skypilot_tpu.infer.engine import (InferenceEngine, SamplingConfig)

__all__ = ['InferenceEngine', 'SamplingConfig']
