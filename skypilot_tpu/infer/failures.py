"""Failure taxonomy for the serving engine.

The supervised decode loop (``infer/server.py``) asks one question of
every exception that escapes ``engine.step()``: is the *device state*
still trustworthy enough to rebuild on?

* **transient** — a step blew up but the process and backend are fine:
  injected chaos, a bad batch, a host-side bug in one tick.  The
  supervisor aborts in-flight slots, has the engine rebuild its device
  caches (donated buffers are invalid after a mid-step exception), and
  keeps serving.
* **fatal** — the device or process is wedged or lying: a hung backend
  (``BackendInitHang`` — an abandoned watchdog thread still holds the
  backend-init lock, see ``parallel/mesh.py``), a watchdog-detected
  stall, an XLA runtime error (device state unknown), a page-accounting
  leak, or the restart budget itself running out.  The replica goes
  unhealthy and every waiter fails fast; recovery is a process restart
  (or, at the fleet layer, a replica replacement).

Classification is by exception *type name* plus a few message markers
rather than imports, so this module stays importable without dragging
in jax (``BackendInitHang`` lives next to the jax bootstrap).
"""
from __future__ import annotations

TRANSIENT = 'transient'
FATAL = 'fatal'

# Type names (not imports — see module docstring) that always mean the
# backend or process can no longer be trusted.
_FATAL_TYPE_NAMES = frozenset({
    'BackendInitHang',
    'StepStallError',
    'PageLeakError',
    'RestartBudgetExceededError',
    'XlaRuntimeError',
})

# Substrings of XLA/PJRT error text that indicate a wedged device even
# when the exception type is generic.
_FATAL_MESSAGE_MARKERS = ('RESOURCE_EXHAUSTED', 'DATA_LOSS',
                          'device halted', 'HBM OOM')


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it produced a result."""


class RequestAbortedError(RuntimeError):
    """One request was dropped (recovery, prefill failure) while the
    engine itself kept serving.  ``__cause__`` carries the trigger."""


class SharedStateError(RuntimeError):
    """An operation that donates the SHARED decode cache failed midway,
    so the cache buffers may be invalid.  Never containable to one
    request: it must propagate to the supervisor, whose recover()
    rebuilds the device state.  Transient by classification."""


class StepStallError(RuntimeError):
    """The watchdog saw a device step exceed the stall timeout — the
    ``BackendInitHang`` class of wedge, detected instead of waited out."""


class RestartBudgetExceededError(RuntimeError):
    """Too many decode-loop restarts inside the rolling window; the
    fault is evidently not transient after all."""


class PageLeakError(RuntimeError):
    """Post-recovery allocator verification failed: pages are still
    referenced or unaccounted for, so the KV pool cannot be reused."""


def wrap_abort(request_id: int, cause: BaseException) -> RequestAbortedError:
    err = RequestAbortedError(f'request {request_id} aborted: {cause!r}')
    err.__cause__ = cause
    return err


def classify(exc: BaseException, context: str = 'decode') -> str:
    """``TRANSIENT`` or ``FATAL`` for an exception out of the decode
    loop (the default) or out of a backend bootstrap
    (``context='init'``).

    ``BackendInitHang`` flips class with the context: mid-serve it
    means the LIVE backend is wedged under in-flight work — fatal,
    replace the process.  During an init/bootstrap (the bench capture
    ladder's first backend touch) it is the known-flaky tunneled-TPU
    first connection (BENCH_r03–r05): a fresh attempt window routinely
    succeeds, so init-context callers retry it under a wall budget
    instead of burning the whole capture attempt on one flake.
    """
    if context not in ('decode', 'init'):
        raise ValueError(
            f"context must be 'decode' or 'init', got {context!r}")
    if isinstance(exc, (MemoryError, KeyboardInterrupt, SystemExit)):
        return FATAL
    if context == 'init' and type(exc).__name__ == 'BackendInitHang':
        return TRANSIENT
    if type(exc).__name__ in _FATAL_TYPE_NAMES:
        return FATAL
    message = str(exc)
    if any(marker in message for marker in _FATAL_MESSAGE_MARKERS):
        return FATAL
    return TRANSIENT
