"""Tokenizer seam for the text-level (OpenAI-compatible) serving API.

The engine works on token ids; the OpenAI surface works on text.  Two
implementations behind one duck-typed interface (`encode(str) ->
List[int]`, `decode(List[int]) -> str`, `eos_id`):

  - `HFTokenizer`: any HuggingFace tokenizer by name — what a real
    checkpoint serves with (the reference's recipes get this
    implicitly from vLLM, e.g. llm/qwen/qwen25-7b.yaml).
  - `ByteTokenizer`: self-contained UTF-8 byte-level fallback —
    ids are bytes offset past the specials, so any text round-trips
    with a 259-entry effective vocab.  This is what test/dev models
    (llama-tiny, random weights) serve with: the API contract —
    framing, SSE streaming, usage accounting — is fully exercised
    without a 100MB tokenizer artifact.

Incremental decode for SSE uses `IncrementalDecoder`: UTF-8 sequences
split across token boundaries must not emit replacement chars
mid-stream, so bytes are buffered until they form valid text.
"""
from __future__ import annotations

import codecs
from typing import List, Optional


class ByteTokenizer:
    """UTF-8 bytes offset by the special tokens: 0=pad 1=bos 2=eos."""

    PAD_ID, BOS_ID, EOS_ID = 0, 1, 2
    _OFFSET = 3

    vocab_size = 256 + _OFFSET

    @property
    def eos_id(self) -> int:
        return self.EOS_ID

    def encode(self, text: str) -> List[int]:
        return [b + self._OFFSET for b in text.encode('utf-8')]

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - self._OFFSET for i in ids
                     if i >= self._OFFSET and i - self._OFFSET < 256)
        return data.decode('utf-8', errors='replace')


class HFTokenizer:
    """Thin adapter over transformers.AutoTokenizer."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer  # type: ignore
        self._tok = AutoTokenizer.from_pretrained(name_or_path)

    @property
    def eos_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    def encode(self, text: str) -> List[int]:
        return self._tok(text)['input_ids']

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


class IncrementalDecoder:
    """Streaming ids -> text without mid-codepoint mojibake.

    ByteTokenizer path: a UTF-8 incremental codec buffers partial
    multibyte sequences across feed() calls.  HF path: re-decode the
    full id list and emit the suffix (HF tokenizers' decode is not
    incremental; suffix-diffing is the standard approach)."""

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._byte_mode = isinstance(tokenizer, ByteTokenizer)
        if self._byte_mode:
            self._codec = codecs.getincrementaldecoder('utf-8')(
                errors='replace')
        else:
            self._ids: List[int] = []
            self._emitted = ''

    def feed(self, token_id: int) -> str:
        """Text newly available after this token ('' if the token
        completes nothing yet, e.g. first byte of a multibyte char)."""
        if self._byte_mode:
            off = ByteTokenizer._OFFSET
            if token_id < off or token_id - off >= 256:
                return ''  # specials produce no text
            return self._codec.decode(bytes([token_id - off]))
        self._ids.append(token_id)
        full = self._tok.decode(self._ids)
        # Hold back while the tail is an incomplete sequence (HF
        # decoders emit U+FFFD for it).
        if full.endswith('�'):
            return ''
        new = full[len(self._emitted):]
        self._emitted = full
        return new

    def flush(self) -> str:
        if self._byte_mode:
            return self._codec.decode(b'', final=True)
        # HF mode: emit any held-back text, dropping only the trailing
        # replacement char(s) from a genuinely incomplete byte
        # sequence — NOT the valid text before them (a generation cut
        # by max_tokens mid-multibyte must still stream its tail).
        tail = self._tok.decode(self._ids)[len(self._emitted):]
        self._emitted += tail
        return tail.rstrip('�')


def load(spec: Optional[str]):
    """None/'' or 'byte' -> ByteTokenizer; anything else -> HF name."""
    if not spec or spec == 'byte':
        return ByteTokenizer()
    return HFTokenizer(spec)
