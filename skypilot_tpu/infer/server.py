"""HTTP serving front-end for the inference engine.

This is what a SkyServe replica runs (the reference's replicas run a
vLLM container instead — `llm/qwen/serve-110b.yaml`).  Stdlib threaded
http.server, matching the rest of the serve stack (serve/controller.py):

  GET  /health              -> 200 {"status": "ok"} once the engine is
                               warm (used by the replica readiness probe)
  POST /generate            -> {"tokens": [[...], ...]}
       body: {"prompt_ids": [[...], ...], "max_new_tokens": N,
              "temperature": T, "top_k": K, "top_p": P, "eos_id": E}
  GET  /v1/models           -> OpenAI model list
  GET  /metrics             -> Prometheus text exposition (v0.0.4) of
                               the process metric registry
  GET  /traces              -> recent request lifecycle traces (JSON;
                               ?limit=N caps the count)
  POST /v1/completions      -> OpenAI completions (stream + non-stream)
  POST /v1/chat/completions -> OpenAI chat (stream + non-stream)

Every request gets an id (the client's X-Request-Id when it is a sane
token, else a generated one), echoed in the X-Request-Id response
header, attached to the engine-side request trace, stamped on access
logs, and included in mid-stream SSE error events so a client can
correlate a broken stream with server logs.

The /v1 surface is the OpenAI-compatible API every reference LLM
recipe serves through vLLM (`llm/qwen/qwen25-7b.yaml:30-33`):
text-level via the --tokenizer seam (HF name, or the built-in byte
tokenizer for test models), SSE token streaming wired to the
continuous-batching engine's incremental decode.  Serving RANDOM
weights over this API is refused unless --allow-random-weights is
passed (noise behind an LLM API is a footgun, not a default).

Default mode is CONTINUOUS BATCHING (engine.ContinuousBatchingEngine):
a dedicated decode-loop thread drives slot-based decode; concurrent
/generate requests are admitted into free KV-cache slots between decode
steps and complete independently — the serving-throughput design the
reference delegates to vLLM (README.md:54).  `--no-continuous` falls
back to request-level batching serialized through a lock.

Run: python -m skypilot_tpu.infer.server --model llama-tiny --port 8000
"""
from __future__ import annotations

import argparse
import http.server
import json
import os
import re
import threading
import time
import urllib.parse
import uuid
from typing import Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.observability import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)


from skypilot_tpu.utils import http_utils

_HTTPServer = http_utils.HighBacklogHTTPServer

# Known routes by method.  Unknown paths collapse to the 'other' route
# label so a URL-scanning client cannot mint unbounded label sets.
_GET_ROUTES = ('/health', '/v1/models', '/metrics', '/traces')
_POST_ROUTES = ('/generate', '/v1/completions', '/v1/chat/completions')

_REQUEST_ID_RE = re.compile(r'[A-Za-z0-9._:-]{1,64}$')


def _http_metrics(registry: Optional[metrics_lib.Registry] = None):
    """Get-or-create the HTTP front-end series (shared by every server
    in the process; also exercised by the metric name-contract test)."""
    r = registry if registry is not None else metrics_lib.get_registry()
    return {
        'requests': r.counter(
            'skytpu_http_requests_total',
            'HTTP requests served, by method/route/status code.',
            labelnames=('method', 'route', 'code')),
        'latency': r.histogram(
            'skytpu_http_request_seconds',
            'Wall-clock seconds per HTTP request (includes queueing '
            'and generation on blocking routes).',
            labelnames=('method', 'route')),
    }


class InferenceServer:

    def __init__(self, model: str = 'llama-tiny', port: int = 8000,
                 host: str = '0.0.0.0', max_batch_size: int = 4,
                 max_seq_len: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 mesh_config: Optional[str] = None,
                 model_overrides=None,
                 continuous: bool = True,
                 prefill_chunk: int = 0,
                 kv_read_bucket: int = 512,
                 quantize=None,
                 kv_cache_dtype: str = 'auto',
                 page_size: int = 0,
                 max_pages: int = 0,
                 compilation_cache_dir=None,
                 tokenizer: Optional[str] = None,
                 allow_random_weights: bool = False,
                 served_model_name: Optional[str] = None,
                 registry: Optional[metrics_lib.Registry] = None
                 ) -> None:
        from skypilot_tpu.parallel import mesh as mesh_lib
        # Hang-proof first backend touch: a wedged tunneled TPU makes
        # this raise (replica exits, probe marks it FAILED) instead of
        # hanging forever behind a 200 /health that never comes.
        mesh_lib.force_platform_and_touch()
        if compilation_cache_dir:
            # Replica readiness is dominated by the prefill/decode
            # compiles: a persistent cache (e.g. on the checkpoint
            # bucket) makes scale-up replicas and restarts come READY
            # in seconds instead of the full compile window.
            mesh_lib.enable_persistent_compilation_cache(
                compilation_cache_dir)
        mesh = None
        if mesh_config:
            kwargs = {}
            for part in mesh_config.split(','):
                if part:
                    k, v = part.split('=')
                    kwargs[k] = int(v)
            mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(**kwargs))
        self.continuous = continuous
        if continuous:
            self.engine = engine_lib.ContinuousBatchingEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                n_slots=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides,
                prefill_chunk=prefill_chunk,
                kv_read_bucket=kv_read_bucket,
                quantize=quantize, kv_cache_dtype=kv_cache_dtype,
                page_size=page_size, max_pages=max_pages,
                registry=registry)
        else:
            if page_size:
                raise ValueError(
                    '--page-size requires continuous batching (the '
                    'paged KV cache is slot-mode only); drop '
                    '--no-continuous.')
            self.engine = engine_lib.InferenceEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                max_batch_size=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides, quantize=quantize,
                kv_cache_dtype=kv_cache_dtype, registry=registry)
        self.registry = self.engine.registry
        self._http_met = _http_metrics(self.registry)
        if not self.engine.loaded_real_weights and \
                not allow_random_weights:
            raise ValueError(
                'refusing to serve randomly initialized weights: pass '
                '--checkpoint-dir (or --allow-random-weights for '
                'tests/dev).')
        from skypilot_tpu.infer import tokenizer as tokenizer_lib
        self.tokenizer = tokenizer_lib.load(tokenizer)
        self.model_name = served_model_name or model
        # Bound on the gap BETWEEN streamed tokens (a stalled decode
        # loop must not pin an SSE connection forever).
        self.stream_token_timeout = float(
            os.environ.get('SKYTPU_STREAM_TOKEN_TIMEOUT_S', '120'))
        # Warm the compile caches (smallest prefill bucket + decode) so
        # /health flips to ready only after the common-path compiles are
        # done.  Other prefill buckets still compile on first use.
        # (Continuous engine: generate() drives step() inline — the
        # decode-loop thread only starts in start().)
        self.engine.generate(
            [[1, 2, 3]],
            engine_lib.SamplingConfig(max_new_tokens=2))
        self._lock = threading.Lock()
        self._port = port
        self._host = host
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._running = False
        self._decode_thread: Optional[threading.Thread] = None
        self._work = threading.Event()
        self._fatal: Optional[BaseException] = None

    def _decode_loop(self) -> None:
        """Single driver of ContinuousBatchingEngine.step(): decodes
        while any slot is occupied, sleeps on the work event when
        idle.  Handler threads only submit()/wait().  A fatal step()
        error (device wedge, OOM) marks the replica UNHEALTHY — the
        readiness probe must stop routing here, and waiters must fail
        fast instead of blocking their full timeout."""
        try:
            while self._running:
                if not self.engine.step():
                    self._work.wait(0.05)
                    self._work.clear()
        except BaseException as e:  # noqa: BLE001 — replica is dead
            logger.exception('decode loop died; marking unhealthy')
            self._fatal = e
            self._running = False
            self.engine.abort(e)

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    def _handle_generate(self, payload: dict,
                         http_request_id: Optional[str] = None) -> dict:
        prompts = payload.get('prompt_ids')
        if not isinstance(prompts, list) or not prompts:
            raise ValueError('prompt_ids must be a non-empty list of '
                             'token-id lists')
        sampling = engine_lib.SamplingConfig(
            temperature=float(payload.get('temperature', 0.0)),
            top_k=int(payload.get('top_k', 0)),
            top_p=float(payload.get('top_p', 1.0)),
            eos_id=payload.get('eos_id'),
            max_new_tokens=int(payload.get('max_new_tokens', 64)),
            seed=(int(payload['seed'])
                  if payload.get('seed') is not None else None))
        if self.continuous:
            # All-or-nothing: a rejected prompt (e.g. overlong) must
            # not strand its siblings decoding with no reader.
            rids = []
            try:
                for p in prompts:
                    rid = self.engine.submit(p, sampling)
                    rids.append(rid)
                    self.engine.traces.annotate(
                        rid, http_request_id=http_request_id)
                self._work.set()
                tokens = [self.engine.wait(r, timeout=600)
                          for r in rids]
            except BaseException:
                for r in rids:
                    self.engine.cancel(r)
                raise
            return {'tokens': tokens}
        with self._lock:
            tokens = self.engine.generate(prompts, sampling)
        return {'tokens': tokens}

    # -- OpenAI-compatible surface ------------------------------------
    def _sampling_for(self, req) -> 'engine_lib.SamplingConfig':
        return engine_lib.SamplingConfig(
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, eos_id=self.tokenizer.eos_id,
            max_new_tokens=req.max_tokens, seed=req.seed)

    def _openai_blocking(self, req, prompt_ids,
                         http_request_id: Optional[str] = None) -> dict:
        from skypilot_tpu.infer import openai_api
        sampling = self._sampling_for(req)
        if self.continuous:
            rid = self.engine.submit(prompt_ids, sampling)
            self.engine.traces.annotate(
                rid, http_request_id=http_request_id)
            self._work.set()
            toks = self.engine.wait(rid, timeout=600)
        else:
            with self._lock:
                toks = self.engine.generate([prompt_ids], sampling)[0]
        eos = self.tokenizer.eos_id
        eos_hit = bool(toks) and eos is not None and toks[-1] == eos
        scanner = openai_api.StopScanner(req.stop)
        text = scanner.feed(self.tokenizer.decode(toks))
        text += scanner.flush()
        finish = 'stop' if (eos_hit or scanner.hit) else 'length'
        return openai_api.completion_response(
            req, text, finish, prompt_tokens=len(prompt_ids),
            completion_tokens=len(toks))

    def _openai_stream(self, req, prompt_ids, handler) -> None:
        """SSE: one `data:` event per decoded text fragment, riding
        the engine's per-token stream queue; ends with the
        finish_reason chunk and `data: [DONE]`."""
        from skypilot_tpu.infer import openai_api
        from skypilot_tpu.infer import tokenizer as tokenizer_lib
        sampling = self._sampling_for(req)
        http_rid = getattr(handler, 'request_id', None)
        rid = self.engine.submit(prompt_ids, sampling, stream=True)
        self.engine.traces.annotate(rid, http_request_id=http_rid)
        self._work.set()

        def _sse(obj) -> None:
            handler.wfile.write(
                f'data: {json.dumps(obj)}\n\n'.encode())
            handler.wfile.flush()

        def _sse_error(message: str) -> None:
            """Mid-stream failure with a live client: an error event
            + [DONE] is the only legal framing (a second HTTP status
            line would be protocol garbage).  Carries the request id so
            the client can quote it back at the server logs/traces."""
            try:
                _sse({'error': {
                    'message': message, 'type': 'server_error',
                    'param': None, 'code': None,
                    'request_id': http_rid}})
                handler.wfile.write(b'data: [DONE]\n\n')
                handler.wfile.flush()
            except OSError:
                pass
            handler.close_connection = True

        decoder = tokenizer_lib.IncrementalDecoder(self.tokenizer)
        scanner = openai_api.StopScanner(req.stop)
        eos = self.tokenizer.eos_id
        n_tokens = 0
        eos_hit = False
        started = False
        try:
            handler.send_response(200)
            handler.send_header('Content-Type', 'text/event-stream')
            handler.send_header('Cache-Control', 'no-cache')
            handler.end_headers()
            started = True
            if req.chat:  # role announcement first
                _sse(openai_api.stream_chunk(req, None, first=True))
            for tok in self.engine.stream(
                    rid, timeout=self.stream_token_timeout):
                n_tokens += 1
                if eos is not None and tok == eos:
                    eos_hit = True
                    continue  # engine completes after eos
                piece = decoder.feed(tok)
                if not piece:
                    continue
                out = scanner.feed(piece)
                if out:
                    _sse(openai_api.stream_chunk(req, out))
                if scanner.hit:
                    self.engine.cancel(rid)
                    break
            tail = decoder.flush()
            out = (scanner.feed(tail) if tail else '') + \
                scanner.flush()
            if out:
                _sse(openai_api.stream_chunk(req, out))
            finish = 'stop' if (eos_hit or scanner.hit) else (
                'length' if n_tokens >= req.max_tokens else 'stop')
            _sse(openai_api.stream_chunk(req, None,
                                         finish_reason=finish))
            handler.wfile.write(b'data: [DONE]\n\n')
            handler.wfile.flush()
        except TimeoutError:
            # Decode stalled past the inter-token bound; stream()
            # already canceled the request.  MUST precede the OSError
            # arm (TimeoutError subclasses it) — the client is still
            # connected and deserves an error event, and the stall
            # must be visible server-side.
            logger.warning(
                f'stream {req.oai_id}: no token within '
                f'{self.stream_token_timeout:.0f}s; terminating SSE')
            self.engine.cancel(rid)
            _sse_error('inter-token timeout: decode stalled')
        except (BrokenPipeError, ConnectionError, OSError):
            # Client went away mid-stream: release the slot so it
            # stops decoding for nobody (also covers a disconnect
            # during header send, before any event went out).
            self.engine.cancel(rid)
            handler.close_connection = True
        except Exception as e:  # pylint: disable=broad-except
            logger.exception(f'stream {req.oai_id} failed mid-flight')
            self.engine.cancel(rid)
            if started:
                _sse_error(f'stream failed: {e}')
            else:
                raise  # headers not sent; do_POST replies cleanly

    def _handle_openai(self, payload: dict, chat: bool,
                       handler) -> Optional[dict]:
        """Returns a JSON body to reply with, or None if the handler
        already streamed the response itself."""
        from skypilot_tpu.infer import openai_api
        parse = openai_api.parse_chat_request if chat else \
            openai_api.parse_completion_request
        req = parse(payload, self.model_name)
        prompt_ids = self.tokenizer.encode(req.prompt_text)
        if not prompt_ids:
            raise openai_api.OpenAIError(
                'prompt encodes to zero tokens')
        if req.stream:
            if not self.continuous:
                raise openai_api.OpenAIError(
                    'stream=true requires continuous batching '
                    '(server started with --no-continuous)')
            self._openai_stream(req, prompt_ids, handler)
            return None
        return self._openai_blocking(
            req, prompt_ids, getattr(handler, 'request_id', None))

    def serve_forever(self) -> None:
        self.start()
        assert self._server is not None
        logger.info(f'inference server on :{self.port}')
        self._server.serve_forever()

    def start(self) -> None:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):

            request_id = '-'
            _last_code = 0

            def log_message(self, format, *args):  # noqa: A002
                # Access logs on the framework logger at DEBUG (JSON
                # when SKYTPU_LOG_JSON=1), stamped with the request id
                # — BaseHTTPRequestHandler would write raw stderr.
                logger.debug(f'{self.address_string()} '
                             f'[{self.request_id}] {format % args}')

            def send_response(self, code, message=None):
                super().send_response(code, message)
                self.send_header('X-Request-Id', self.request_id)
                self._last_code = code

            def _reply(self, code: int, body: dict,
                       allow: Optional[str] = None) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(data)))
                if allow is not None:
                    self.send_header('Allow', allow)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                self._dispatch('GET')

            def do_POST(self):  # noqa: N802
                self._dispatch('POST')

            def _dispatch(self, method: str) -> None:
                incoming = self.headers.get('X-Request-Id', '')
                self.request_id = (
                    incoming if _REQUEST_ID_RE.match(incoming)
                    else 'req-' + uuid.uuid4().hex[:16])
                self._last_code = 0
                route = self.path.split('?', 1)[0]
                known = route in _GET_ROUTES or route in _POST_ROUTES
                label = route if known else 'other'
                met = outer._http_met  # pylint: disable=protected-access
                start = time.perf_counter()
                try:
                    if method == 'GET':
                        self._do_get(route)
                    else:
                        self._do_post(route)
                finally:
                    met['latency'].labels(
                        method=method, route=label).observe(
                            time.perf_counter() - start)
                    met['requests'].labels(
                        method=method, route=label,
                        code=str(self._last_code or 0)).inc()

            def _do_get(self, route: str) -> None:
                if route == '/health':
                    if outer._fatal is not None:  # pylint: disable=protected-access
                        self._reply(503, {
                            'status': 'unhealthy',
                            'error': repr(outer._fatal)})  # pylint: disable=protected-access
                    else:
                        self._reply(200, {'status': 'ok'})
                elif route == '/v1/models':
                    self._reply(200, {
                        'object': 'list',
                        'data': [{'id': outer.model_name,
                                  'object': 'model',
                                  'created': 0,
                                  'owned_by': 'skypilot-tpu'}]})
                elif route == '/metrics':
                    data = outer.registry.expose().encode()
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     metrics_lib.CONTENT_TYPE_LATEST)
                    self.send_header('Content-Length', str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif route == '/traces':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        limit = int(query.get('limit', ['100'])[0])
                    except ValueError:
                        limit = 100
                    store = outer.engine.traces
                    self._reply(200, {
                        'traces': store.recent(limit),
                        'in_flight': store.inflight_count})
                elif route in _POST_ROUTES:
                    self._reply(405, {'error': 'method not allowed'},
                                allow='POST')
                else:
                    self._reply(404, {'error': 'not found'})

            def _do_post(self, route: str) -> None:
                from skypilot_tpu.infer import openai_api
                if route not in _POST_ROUTES:
                    if route in _GET_ROUTES:
                        self._reply(405,
                                    {'error': 'method not allowed'},
                                    allow='GET')
                    else:
                        self._reply(404, {'error': 'not found'})
                    return
                try:
                    length = int(self.headers.get('Content-Length', 0))
                    payload = json.loads(self.rfile.read(length) or b'{}')
                    if route == '/generate':
                        self._reply(200, outer._handle_generate(  # pylint: disable=protected-access
                            payload, self.request_id))
                        return
                    body = outer._handle_openai(  # pylint: disable=protected-access
                        payload, chat=route.endswith(
                            '/chat/completions'), handler=self)
                    if body is not None:
                        self._reply(200, body)
                except openai_api.OpenAIError as e:
                    self._reply(e.status, e.body())
                except ValueError as e:
                    if route == '/generate':
                        self._reply(400, {'error': str(e)})
                    else:
                        self._reply(
                            400, openai_api.OpenAIError(str(e)).body())
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception('generate failed')
                    self._reply(500, {'error': str(e)})

        self._server = _HTTPServer((self._host, self._port), Handler)
        if self.continuous and self._decode_thread is None:
            self._running = True
            self._decode_thread = threading.Thread(
                target=self._decode_loop, daemon=True,
                name='skytpu-decode-loop')
            self._decode_thread.start()

    def shutdown(self) -> None:
        self._running = False
        self._work.set()
        if self._decode_thread is not None:
            self._decode_thread.join(timeout=5)
            self._decode_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--port', type=int, default=8000)
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--max-batch-size', type=int, default=4)
    parser.add_argument('--max-seq-len', type=int, default=None)
    parser.add_argument('--checkpoint-dir', default=None,
                        help='trainer Orbax checkpoint to serve '
                             '(bucket-mounted path)')
    parser.add_argument('--mesh', default=None,
                        help="shard over local devices, e.g. 'tensor=4'")
    parser.add_argument('--no-continuous', dest='continuous',
                        action='store_false', default=True,
                        help='Request-level batching instead of '
                             'continuous (slot-based) batching.')
    parser.add_argument('--prefill-chunk', type=int, default=0,
                        help='Chunked prefill: process long prompts '
                             'this many tokens per decode tick so live '
                             'requests keep generating (0 = whole '
                             'prompt at admission).')
    parser.add_argument('--quantize', default=None,
                        choices=['int8'],
                        help='Weight-only int8 serving: halves param '
                             'HBM traffic; composes with --mesh '
                             '(q8/scale leaves shard like their float '
                             'kernels).')
    parser.add_argument('--kv-cache-dtype', default='auto',
                        choices=['auto', 'int8'],
                        help='KV-cache storage dtype: int8 stores '
                             'cache rows quantized with per-(kv-head, '
                             'position) f32 absmax scales — halves '
                             'decode cache HBM traffic vs bf16 and '
                             'doubles the contexts that fit; dequant '
                             'stays fused in the attention epilogue. '
                             'Composes with --quantize (weights).')
    parser.add_argument('--page-size', type=int, default=0,
                        help='Paged KV cache: split the cache into '
                             'pages of this many positions (power of '
                             'two dividing --max-seq-len and the '
                             'prefill bucket) — decode HBM reads '
                             'track each request\'s LIVE context '
                             'instead of max-seq-len, and requests '
                             'sharing a prompt prefix share its '
                             'pages (prefilled once, refcounted). '
                             '0 = contiguous per-slot rows. Requires '
                             'continuous batching.')
    parser.add_argument('--max-pages', type=int, default=0,
                        help='Page-pool size for --page-size (incl. '
                             'the reserved null page). Default sizes '
                             'the pool so every slot can fill its '
                             'row; smaller values oversubscribe — '
                             'admission then waits for free pages '
                             '(backpressure) instead of free slots.')
    parser.add_argument('--compilation-cache-dir', default=None,
                        help='Persistent XLA compile cache: '
                             'scale-up replicas/restarts skip the '
                             'prefill+decode compiles and come '
                             'READY in seconds.')
    parser.add_argument('--platform', default=None,
                        help="Force a jax platform (e.g. 'cpu' for "
                             'tests; env JAX_PLATFORMS alone is not '
                             'enough on tunneled-TPU hosts).')
    parser.add_argument('--tokenizer', default=None,
                        help='HF tokenizer name for the /v1 text API; '
                             "default 'byte' (built-in UTF-8 byte "
                             'tokenizer, test/dev models).')
    parser.add_argument('--allow-random-weights', action='store_true',
                        default=False,
                        help='Serve without a checkpoint (randomly '
                             'initialized weights). Tests/dev only; '
                             'without this flag the server refuses '
                             'to start paramless.')
    parser.add_argument('--served-model-name', default=None,
                        help='Model id reported by /v1/models and in '
                             'OpenAI responses (default: --model).')
    parser.add_argument('--kv-read-bucket', type=int, default=512,
                        help='Decode attention reads only the live '
                             'cache prefix, rounded up to this bucket '
                             '(one compile per bucket crossed; big HBM '
                             'savings at long max-seq-len). 0 reads '
                             'the full cache and compiles decode '
                             'exactly once.')
    args = parser.parse_args()
    if args.platform:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh_lib.force_platform_and_touch(args.platform)
    InferenceServer(model=args.model, port=args.port, host=args.host,
                    max_batch_size=args.max_batch_size,
                    max_seq_len=args.max_seq_len,
                    checkpoint_dir=args.checkpoint_dir,
                    mesh_config=args.mesh,
                    continuous=args.continuous,
                    prefill_chunk=args.prefill_chunk,
                    kv_read_bucket=args.kv_read_bucket,
                    quantize=args.quantize,
                    kv_cache_dtype=args.kv_cache_dtype,
                    page_size=args.page_size,
                    max_pages=args.max_pages,
                    compilation_cache_dir=args.compilation_cache_dir,
                    tokenizer=args.tokenizer,
                    allow_random_weights=args.allow_random_weights,
                    served_model_name=args.served_model_name,
                    ).serve_forever()


if __name__ == '__main__':
    main()
