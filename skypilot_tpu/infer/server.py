"""HTTP serving front-end for the inference engine.

This is what a SkyServe replica runs (the reference's replicas run a
vLLM container instead — `llm/qwen/serve-110b.yaml`).  Stdlib threaded
http.server, matching the rest of the serve stack (serve/controller.py):

  GET  /health              -> 200 {"status": "ok"} once the engine is
                               warm (used by the replica readiness probe)
  POST /generate            -> {"tokens": [[...], ...]}
       body: {"prompt_ids": [[...], ...], "max_new_tokens": N,
              "temperature": T, "top_k": K, "top_p": P, "eos_id": E}

Default mode is CONTINUOUS BATCHING (engine.ContinuousBatchingEngine):
a dedicated decode-loop thread drives slot-based decode; concurrent
/generate requests are admitted into free KV-cache slots between decode
steps and complete independently — the serving-throughput design the
reference delegates to vLLM (README.md:54).  `--no-continuous` falls
back to request-level batching serialized through a lock.

Run: python -m skypilot_tpu.infer.server --model llama-tiny --port 8000
"""
from __future__ import annotations

import argparse
import http.server
import json
import os
import threading
from typing import Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.infer import engine as engine_lib

logger = sky_logging.init_logger(__name__)


from skypilot_tpu.utils import http_utils

_HTTPServer = http_utils.HighBacklogHTTPServer


class InferenceServer:

    def __init__(self, model: str = 'llama-tiny', port: int = 8000,
                 host: str = '0.0.0.0', max_batch_size: int = 4,
                 max_seq_len: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 mesh_config: Optional[str] = None,
                 model_overrides=None,
                 continuous: bool = True,
                 prefill_chunk: int = 0,
                 kv_read_bucket: int = 512,
                 quantize=None,
                 compilation_cache_dir=None) -> None:
        from skypilot_tpu.parallel import mesh as mesh_lib
        # Hang-proof first backend touch: a wedged tunneled TPU makes
        # this raise (replica exits, probe marks it FAILED) instead of
        # hanging forever behind a 200 /health that never comes.
        mesh_lib.force_platform_and_touch()
        if compilation_cache_dir:
            # Replica readiness is dominated by the prefill/decode
            # compiles: a persistent cache (e.g. on the checkpoint
            # bucket) makes scale-up replicas and restarts come READY
            # in seconds instead of the full compile window.
            mesh_lib.enable_persistent_compilation_cache(
                compilation_cache_dir)
        mesh = None
        if mesh_config:
            kwargs = {}
            for part in mesh_config.split(','):
                if part:
                    k, v = part.split('=')
                    kwargs[k] = int(v)
            mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(**kwargs))
        self.continuous = continuous
        if continuous:
            self.engine = engine_lib.ContinuousBatchingEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                n_slots=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides,
                prefill_chunk=prefill_chunk,
                kv_read_bucket=kv_read_bucket,
                quantize=quantize)
        else:
            self.engine = engine_lib.InferenceEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                max_batch_size=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides, quantize=quantize)
        # Warm the compile caches (smallest prefill bucket + decode) so
        # /health flips to ready only after the common-path compiles are
        # done.  Other prefill buckets still compile on first use.
        # (Continuous engine: generate() drives step() inline — the
        # decode-loop thread only starts in start().)
        self.engine.generate(
            [[1, 2, 3]],
            engine_lib.SamplingConfig(max_new_tokens=2))
        self._lock = threading.Lock()
        self._port = port
        self._host = host
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._running = False
        self._decode_thread: Optional[threading.Thread] = None
        self._work = threading.Event()
        self._fatal: Optional[BaseException] = None

    def _decode_loop(self) -> None:
        """Single driver of ContinuousBatchingEngine.step(): decodes
        while any slot is occupied, sleeps on the work event when
        idle.  Handler threads only submit()/wait().  A fatal step()
        error (device wedge, OOM) marks the replica UNHEALTHY — the
        readiness probe must stop routing here, and waiters must fail
        fast instead of blocking their full timeout."""
        try:
            while self._running:
                if not self.engine.step():
                    self._work.wait(0.05)
                    self._work.clear()
        except BaseException as e:  # noqa: BLE001 — replica is dead
            logger.exception('decode loop died; marking unhealthy')
            self._fatal = e
            self._running = False
            self.engine.abort(e)

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    def _handle_generate(self, payload: dict) -> dict:
        prompts = payload.get('prompt_ids')
        if not isinstance(prompts, list) or not prompts:
            raise ValueError('prompt_ids must be a non-empty list of '
                             'token-id lists')
        sampling = engine_lib.SamplingConfig(
            temperature=float(payload.get('temperature', 0.0)),
            top_k=int(payload.get('top_k', 0)),
            top_p=float(payload.get('top_p', 1.0)),
            eos_id=payload.get('eos_id'),
            max_new_tokens=int(payload.get('max_new_tokens', 64)),
            seed=(int(payload['seed'])
                  if payload.get('seed') is not None else None))
        if self.continuous:
            # All-or-nothing: a rejected prompt (e.g. overlong) must
            # not strand its siblings decoding with no reader.
            rids = []
            try:
                for p in prompts:
                    rids.append(self.engine.submit(p, sampling))
                self._work.set()
                tokens = [self.engine.wait(r, timeout=600)
                          for r in rids]
            except BaseException:
                for r in rids:
                    self.engine.cancel(r)
                raise
            return {'tokens': tokens}
        with self._lock:
            tokens = self.engine.generate(prompts, sampling)
        return {'tokens': tokens}

    def serve_forever(self) -> None:
        self.start()
        assert self._server is not None
        logger.info(f'inference server on :{self.port}')
        self._server.serve_forever()

    def start(self) -> None:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):

            def log_message(self, *args):  # quiet
                del args

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path == '/health':
                    if outer._fatal is not None:  # pylint: disable=protected-access
                        self._reply(503, {
                            'status': 'unhealthy',
                            'error': repr(outer._fatal)})  # pylint: disable=protected-access
                    else:
                        self._reply(200, {'status': 'ok'})
                else:
                    self._reply(404, {'error': 'not found'})

            def do_POST(self):  # noqa: N802
                if self.path != '/generate':
                    self._reply(404, {'error': 'not found'})
                    return
                try:
                    length = int(self.headers.get('Content-Length', 0))
                    payload = json.loads(self.rfile.read(length) or b'{}')
                    self._reply(200, outer._handle_generate(payload))  # pylint: disable=protected-access
                except ValueError as e:
                    self._reply(400, {'error': str(e)})
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception('generate failed')
                    self._reply(500, {'error': str(e)})

        self._server = _HTTPServer((self._host, self._port), Handler)
        if self.continuous and self._decode_thread is None:
            self._running = True
            self._decode_thread = threading.Thread(
                target=self._decode_loop, daemon=True,
                name='skytpu-decode-loop')
            self._decode_thread.start()

    def shutdown(self) -> None:
        self._running = False
        self._work.set()
        if self._decode_thread is not None:
            self._decode_thread.join(timeout=5)
            self._decode_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--port', type=int, default=8000)
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--max-batch-size', type=int, default=4)
    parser.add_argument('--max-seq-len', type=int, default=None)
    parser.add_argument('--checkpoint-dir', default=None,
                        help='trainer Orbax checkpoint to serve '
                             '(bucket-mounted path)')
    parser.add_argument('--mesh', default=None,
                        help="shard over local devices, e.g. 'tensor=4'")
    parser.add_argument('--no-continuous', dest='continuous',
                        action='store_false', default=True,
                        help='Request-level batching instead of '
                             'continuous (slot-based) batching.')
    parser.add_argument('--prefill-chunk', type=int, default=0,
                        help='Chunked prefill: process long prompts '
                             'this many tokens per decode tick so live '
                             'requests keep generating (0 = whole '
                             'prompt at admission).')
    parser.add_argument('--quantize', default=None,
                        choices=['int8'],
                        help='Weight-only int8 serving: halves param '
                             'HBM traffic; composes with --mesh '
                             '(q8/scale leaves shard like their float '
                             'kernels).')
    parser.add_argument('--compilation-cache-dir', default=None,
                        help='Persistent XLA compile cache: '
                             'scale-up replicas/restarts skip the '
                             'prefill+decode compiles and come '
                             'READY in seconds.')
    parser.add_argument('--platform', default=None,
                        help="Force a jax platform (e.g. 'cpu' for "
                             'tests; env JAX_PLATFORMS alone is not '
                             'enough on tunneled-TPU hosts).')
    parser.add_argument('--kv-read-bucket', type=int, default=512,
                        help='Decode attention reads only the live '
                             'cache prefix, rounded up to this bucket '
                             '(one compile per bucket crossed; big HBM '
                             'savings at long max-seq-len). 0 reads '
                             'the full cache and compiles decode '
                             'exactly once.')
    args = parser.parse_args()
    if args.platform:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh_lib.force_platform_and_touch(args.platform)
    InferenceServer(model=args.model, port=args.port, host=args.host,
                    max_batch_size=args.max_batch_size,
                    max_seq_len=args.max_seq_len,
                    checkpoint_dir=args.checkpoint_dir,
                    mesh_config=args.mesh,
                    continuous=args.continuous,
                    prefill_chunk=args.prefill_chunk,
                    kv_read_bucket=args.kv_read_bucket,
                    quantize=args.quantize,
                    compilation_cache_dir=args.compilation_cache_dir,
                    ).serve_forever()


if __name__ == '__main__':
    main()
