"""HTTP serving front-end for the inference engine.

This is what a SkyServe replica runs (the reference's replicas run a
vLLM container instead — `llm/qwen/serve-110b.yaml`).  Stdlib threaded
http.server, matching the rest of the serve stack (serve/controller.py):

  GET  /health              -> 200 {"status": "ok"} once the engine is
                               warm (used by the replica readiness probe)
  POST /generate            -> {"tokens": [[...], ...]}
       body: {"prompt_ids": [[...], ...], "max_new_tokens": N,
              "temperature": T, "top_k": K, "top_p": P, "eos_id": E}
  GET  /v1/models           -> OpenAI model list
  GET  /metrics             -> Prometheus text exposition (v0.0.4) of
                               the process metric registry
  GET  /traces              -> recent request lifecycle traces (JSON;
                               ?limit=N caps the count,
                               ?request_id=X filters by the external
                               X-Request-Id — the router's stitch key)
  GET  /events              -> flight-recorder ring (restarts, stalls,
                               drains, chaos injections; ?limit=N)
  POST /v1/completions      -> OpenAI completions (stream + non-stream)
  POST /v1/chat/completions -> OpenAI chat (stream + non-stream)
  POST /drain               -> stop admission, finish in-flight work,
                               then shut the server down (graceful
                               replica retirement; /health reports
                               "draining" while it runs).  Body
                               {"migrate": true, "targets": [urls]}
                               additionally checkpoints every live
                               decode slot as a SKHO slot artifact and
                               relays it to a survivor's /handoff, so
                               in-flight streams finish byte-identical
                               on the survivor instead of racing the
                               drain window
  GET  /kv_prefix?hashes=..  -> fleet prefix-cache tier: the longest
                               leading run of the comma-separated
                               chain hashes resident in this replica's
                               host-RAM spill tier, as a SKHO
                               kv_prefix artifact (404 when the tier
                               is off or holds none of the chain)

Failure containment: the decode loop runs SUPERVISED — a transient
step() failure aborts the in-flight slots, rebuilds the engine's
device state, and restarts the loop (bounded restarts per rolling
window); fatal failures (wedged backend, watchdog-detected stall,
page-accounting leak) mark the replica unhealthy and fail every
waiter fast.  Every request carries a deadline (payload `deadline_s`,
default SKYTPU_REQUEST_DEADLINE_S); admission sheds load with 503 +
Retry-After when the queue is full, the server is draining, or the
estimated queue wait already exceeds the request's deadline.

Every request gets an id (the client's X-Request-Id when it is a sane
token, else a generated one), echoed in the X-Request-Id response
header, attached to the engine-side request trace, stamped on access
logs, and included in mid-stream SSE error events so a client can
correlate a broken stream with server logs.

The /v1 surface is the OpenAI-compatible API every reference LLM
recipe serves through vLLM (`llm/qwen/qwen25-7b.yaml:30-33`):
text-level via the --tokenizer seam (HF name, or the built-in byte
tokenizer for test models), SSE token streaming wired to the
continuous-batching engine's incremental decode.  Serving RANDOM
weights over this API is refused unless --allow-random-weights is
passed (noise behind an LLM API is a footgun, not a default).

Default mode is CONTINUOUS BATCHING (engine.ContinuousBatchingEngine):
a dedicated decode-loop thread drives slot-based decode; concurrent
/generate requests are admitted into free KV-cache slots between decode
steps and complete independently — the serving-throughput design the
reference delegates to vLLM (README.md:54).  `--no-continuous` falls
back to request-level batching serialized through a lock.

Run: python -m skypilot_tpu.infer.server --model llama-tiny --port 8000
"""
from __future__ import annotations

import argparse
import collections
import http.server
import json
import os
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Iterator, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import failures
from skypilot_tpu.infer import handoff as handoff_lib
from skypilot_tpu.protocol import (DEADLINE_HEADER,
                                   HANDOFF_FAIL_CLOSED)
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.observability import ledger as ledger_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing as tracing_lib
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import retry as retry_lib

logger = sky_logging.init_logger(__name__)


from skypilot_tpu.utils import http_utils

_HTTPServer = http_utils.HighBacklogHTTPServer

# Known routes by method.  Unknown paths collapse to the 'other' route
# label so a URL-scanning client cannot mint unbounded label sets.
_GET_ROUTES = ('/health', '/v1/models', '/metrics', '/traces',
               '/events', '/kv_prefix', '/profile/steps',
               '/profile/timeline')
_POST_ROUTES = ('/generate', '/v1/completions', '/v1/chat/completions',
                '/drain', '/handoff', '/profile/device')

_REQUEST_ID_RE = re.compile(r'[A-Za-z0-9._:-]{1,64}$')

# /health status -> skytpu_health_state gauge value.
_HEALTH_STATES = {'ok': 0.0, 'draining': 1.0, 'unhealthy': 2.0}


class _Shed(Exception):
    """Admission-time load shed: becomes a 503 with a Retry-After
    header instead of queueing work the request's deadline cannot
    survive."""

    def __init__(self, message: str, reason: str, retry_after: int = 1):
        super().__init__(message)
        self.reason = reason
        self.retry_after = retry_after


class ProfileActiveError(Exception):
    """POST /profile/device while a device capture is already armed or
    running — single-flight, becomes a 409 (retry after the window)."""


def _http_metrics(registry: Optional[metrics_lib.Registry] = None):
    """Get-or-create the HTTP front-end series (shared by every server
    in the process; also exercised by the metric name-contract test)."""
    r = registry if registry is not None else metrics_lib.get_registry()
    return {
        'requests': r.counter(
            'skytpu_http_requests_total',
            'HTTP requests served, by method/route/status code.',
            labelnames=('method', 'route', 'code')),
        'latency': r.histogram(
            'skytpu_http_request_seconds',
            'Wall-clock seconds per HTTP request (includes queueing '
            'and generation on blocking routes).',
            labelnames=('method', 'route')),
    }


def _failure_metrics(registry: Optional[metrics_lib.Registry] = None):
    """Failure-containment series for the supervised decode loop."""
    r = registry if registry is not None else metrics_lib.get_registry()
    return {
        'restarts': r.counter(
            'skytpu_decode_loop_restarts_total',
            'Supervised decode-loop restarts after a transient step '
            'failure (in-flight slots aborted, device state rebuilt).'),
        'stalls': r.counter(
            'skytpu_decode_stalls_detected_total',
            'Hung device steps detected by the watchdog (step exceeded '
            'SKYTPU_STEP_STALL_TIMEOUT_S; replica marked unhealthy).'),
        'shed': r.counter(
            'skytpu_requests_shed_total',
            'Requests rejected at admission (503 + Retry-After), by '
            'reason.',
            labelnames=('reason',)),
        'health': r.gauge(
            'skytpu_health_state',
            'Replica health as reported by /health: 0=ok, 1=draining, '
            '2=unhealthy.'),
        # Registered eagerly (chaos itself lazily get-or-creates it on
        # first injection) so /metrics always exposes the series.
        'chaos': chaos.register_metric(r),
    }


class InferenceServer:

    def __init__(self, model: str = 'llama-tiny', port: int = 8000,
                 host: str = '0.0.0.0', max_batch_size: int = 4,
                 max_seq_len: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 mesh_config: Optional[str] = None,
                 model_overrides=None,
                 continuous: bool = True,
                 prefill_chunk: int = 0,
                 kv_read_bucket: int = 512,
                 quantize=None,
                 kv_cache_dtype: str = 'auto',
                 page_size: int = 0,
                 max_pages: int = 0,
                 compilation_cache_dir=None,
                 tokenizer: Optional[str] = None,
                 allow_random_weights: bool = False,
                 served_model_name: Optional[str] = None,
                 registry: Optional[metrics_lib.Registry] = None,
                 default_deadline_s: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 stall_timeout_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 restart_window_s: Optional[float] = None,
                 draft_model: Optional[str] = None,
                 draft_checkpoint_dir: Optional[str] = None,
                 draft_overrides=None,
                 spec_k: int = 0,
                 async_pipeline: bool = True,
                 decode_kernel: str = 'auto',
                 prefill_kernel: str = 'auto',
                 prefill_mix_budget: int = 0,
                 role: str = 'both',
                 decode_peers: Optional[str] = None,
                 host_cache_bytes: int = 0,
                 ) -> None:
        from skypilot_tpu.parallel import mesh as mesh_lib
        # Hang-proof first backend touch: a wedged tunneled TPU makes
        # this raise (replica exits, probe marks it FAILED) instead of
        # hanging forever behind a 200 /health that never comes.
        mesh_lib.force_platform_and_touch()
        if compilation_cache_dir:
            # Replica readiness is dominated by the prefill/decode
            # compiles: a persistent cache (e.g. on the checkpoint
            # bucket) makes scale-up replicas and restarts come READY
            # in seconds instead of the full compile window.
            mesh_lib.enable_persistent_compilation_cache(
                compilation_cache_dir)
        mesh = None
        if mesh_config:
            kwargs = {}
            for part in mesh_config.split(','):
                if part:
                    k, v = part.split('=')
                    kwargs[k] = int(v)
            mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(**kwargs))
        self.continuous = continuous
        # Disaggregated serving: a prefill-role replica hands finished
        # prefills to a decode-role replica as a KV artifact instead of
        # decoding them itself (engine validates the role value).
        self.role = role
        self._decode_peers = [u.strip().rstrip('/')
                              for u in (decode_peers or '').split(',')
                              if u.strip()]
        if role != 'both' and not continuous:
            raise ValueError(
                '--role prefill/decode requires continuous batching '
                '(the handoff rides the slot engine); drop '
                '--no-continuous.')
        if continuous:
            self.engine = engine_lib.ContinuousBatchingEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                n_slots=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides,
                prefill_chunk=prefill_chunk,
                kv_read_bucket=kv_read_bucket,
                quantize=quantize, kv_cache_dtype=kv_cache_dtype,
                page_size=page_size, max_pages=max_pages,
                registry=registry, draft_model=draft_model,
                draft_checkpoint_dir=draft_checkpoint_dir,
                draft_overrides=draft_overrides, spec_k=spec_k,
                async_pipeline=async_pipeline,
                decode_kernel=decode_kernel,
                prefill_kernel=prefill_kernel,
                prefill_mix_budget=prefill_mix_budget,
                role=role,
                host_cache_bytes=host_cache_bytes)
        else:
            if decode_kernel != 'auto':
                raise ValueError(
                    '--decode-kernel requires continuous batching '
                    '(paged decode attention is slot-mode only); drop '
                    '--no-continuous.')
            if prefill_kernel != 'auto' or prefill_mix_budget:
                raise ValueError(
                    '--prefill-kernel/--prefill-mix-budget require '
                    'continuous batching (chunked prefill is a '
                    'slot-engine path); drop --no-continuous.')
            if page_size:
                raise ValueError(
                    '--page-size requires continuous batching (the '
                    'paged KV cache is slot-mode only); drop '
                    '--no-continuous.')
            if spec_k or draft_model:
                raise ValueError(
                    '--spec-k/--draft-model require continuous '
                    'batching (speculation is a slot-mode decode '
                    'path); drop --no-continuous.')
            if host_cache_bytes:
                raise ValueError(
                    '--host-cache-mb requires continuous batching '
                    '(the host tier spills paged KV); drop '
                    '--no-continuous.')
            self.engine = engine_lib.InferenceEngine(
                model=model, mesh=mesh, checkpoint_dir=checkpoint_dir,
                max_batch_size=max_batch_size,
                max_seq_len=max_seq_len,
                model_overrides=model_overrides, quantize=quantize,
                kv_cache_dtype=kv_cache_dtype, registry=registry)
        self.registry = self.engine.registry
        self._http_met = _http_metrics(self.registry)
        if not self.engine.loaded_real_weights and \
                not allow_random_weights:
            raise ValueError(
                'refusing to serve randomly initialized weights: pass '
                '--checkpoint-dir (or --allow-random-weights for '
                'tests/dev).')
        from skypilot_tpu.infer import tokenizer as tokenizer_lib
        self.tokenizer = tokenizer_lib.load(tokenizer)
        self.model_name = served_model_name or model
        # Bound on the gap BETWEEN streamed tokens (a stalled decode
        # loop must not pin an SSE connection forever).
        self.stream_token_timeout = float(
            os.environ.get('SKYTPU_STREAM_TOKEN_TIMEOUT_S', '120'))
        # Warm the compile caches (smallest prefill bucket + decode) so
        # /health flips to ready only after the common-path compiles are
        # done.  Other prefill buckets still compile on first use.
        # (Continuous engine: generate() drives step() inline — the
        # decode-loop thread only starts in start().)
        self.engine.generate(
            [[1, 2, 3]],
            engine_lib.SamplingConfig(max_new_tokens=2))
        self._lock = threading.Lock()
        self._port = port
        self._host = host
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._running = False
        self._decode_thread: Optional[threading.Thread] = None
        self._work = threading.Event()
        self._fatal: Optional[BaseException] = None
        # -- failure containment (ctor args override the env knobs) ---
        self.default_deadline_s = (
            default_deadline_s if default_deadline_s is not None else
            float(os.environ.get('SKYTPU_REQUEST_DEADLINE_S', '600')))
        self.max_queue_depth = (
            max_queue_depth if max_queue_depth is not None else
            int(os.environ.get('SKYTPU_MAX_QUEUE_DEPTH',
                               str(8 * max_batch_size))))
        self.stall_timeout_s = (
            stall_timeout_s if stall_timeout_s is not None else
            float(os.environ.get('SKYTPU_STEP_STALL_TIMEOUT_S', '120')))
        self.max_restarts = (
            max_restarts if max_restarts is not None else
            int(os.environ.get('SKYTPU_LOOP_MAX_RESTARTS', '5')))
        self.restart_window_s = (
            restart_window_s if restart_window_s is not None else
            float(os.environ.get('SKYTPU_LOOP_RESTART_WINDOW_S', '60')))
        self.drain_timeout_s = float(
            os.environ.get('SKYTPU_DRAIN_TIMEOUT_S', '600'))
        self.shutdown_join_s = float(
            os.environ.get('SKYTPU_SHUTDOWN_JOIN_S', '5'))
        self._fail_met = _failure_metrics(self.registry)
        # Flight recorder (GET /events): decode-loop restarts, stalls,
        # drains, and chaos injections — the replica-side half of the
        # fleet's post-incident story.
        self.events = events_lib.EventRing(registry=self.registry,
                                           source='replica')
        chaos.add_event_sink(self._record_chaos_event)
        self._draining = False
        self._drain_lock = threading.Lock()
        # Live migration: survivor replicas a migrate-drain relays slot
        # artifacts to, and a count of relays in flight so the drain
        # window outlives every relayed stream (its own lock — flat
        # hierarchy, never held across another acquire or any I/O).
        self._migrate_targets: list = []
        self._relay_lock = threading.Lock()
        self._active_relays = 0
        self._drain_thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # monotonic ts of the step() call in flight, None between steps;
        # written only by the decode loop, read by the watchdog.
        self._step_started: Optional[float] = None
        # On-demand device profiler (POST /profile/device): state dict
        # {'remaining', 'dir', 'active'} or None.  Single-flight —
        # armed by a handler thread under _profile_lock, consumed by
        # the decode loop in _profile_tick (jax.profiler traces are
        # process-global, so two overlapping windows would corrupt
        # each other; the second POST gets a 409 instead).
        self._profile_lock = threading.Lock()
        self._profile: Optional[dict] = None
        # Chaos arms AFTER the warmup generate: injected faults must
        # exercise the supervised loop, not the readiness compile.
        chaos.init_from_env()
        self._set_health('ok')

    def _record_chaos_event(self, point: str) -> None:
        self.events.record('chaos_injection', point=point)

    def _set_health(self, state: str) -> None:
        self._health = state
        self._fail_met['health'].set(_HEALTH_STATES[state])

    def health_detail(self) -> dict:
        """Replica facts for ``GET /health?verbose=1``: the routing-
        relevant geometry (page_size anchors the router's prefix-
        affinity granularity) and the allocator leak report the chaos
        e2e asserts on without reaching into process internals."""
        eng = self.engine
        detail = {
            'model': self.model_name,
            # The router's role discovery: prefill-role replicas get
            # client traffic + a decode target; decode-role replicas
            # get /handoff traffic only.  Stub servers (observability
            # tests bind health_detail to a bare namespace) predate
            # roles and read as 'both'.
            'role': getattr(self, 'role', 'both'),
            'n_slots': eng.n_slots,
            'page_size': eng.page_size,
            'queue_depth': eng.queue_depth,
            'leak_report': eng.allocator_leak_report(),
        }
        free = eng.free_pages()
        if free is not None:
            detail['free_pages'] = free
        spec = getattr(eng, 'speculation_info', lambda: None)()
        if spec is not None:
            # Router/fleet views key off the acceptance rate: a replica
            # whose speculation stopped paying for itself is visible
            # without a metrics scrape.
            detail['speculation'] = spec
        pipe = getattr(eng, 'pipeline_info', None)
        if pipe is not None:
            # Async decode pipeline state: mode, in-flight depth,
            # fetch-thread liveness, overlapped-step count.
            detail['pipeline'] = pipe()
        dk = getattr(eng, 'decode_kernel_info', None)
        if dk is not None:
            # Paged decode-attention implementation: resolved path
            # (fused Pallas vs XLA gather), page geometry, and whether
            # the kernel runs in interpreter mode (off-TPU tests only).
            detail['decode_kernel'] = dk()
        pk = getattr(eng, 'prefill_kernel_info', None)
        if pk is not None:
            # Chunked-prefill implementation: resolved path (fused
            # ragged-prefill Pallas vs XLA sliced-prefix), the
            # mixed-batch token budget, and pending prompt count.
            detail['prefill_kernel'] = pk()
        hc = getattr(eng, 'host_cache_stats', None)
        if hc is not None:
            stats = hc()
            if stats is not None:
                # Fleet prefix-cache tier: host-RAM spill occupancy,
                # hit/miss/rehydrate counters — what the dashboard's
                # cache-tier columns and the fleet fetch path key off.
                detail['fleet_cache'] = stats
        sh = getattr(eng, 'sharding_info', None)
        if sh is not None:
            # Tensor-parallel geometry: mesh axis sizes, how the KV
            # pool sharded (kv_heads fast path vs page-/sequence-
            # sharded fallback), and kv-heads per shard.
            detail['sharding'] = sh()
        li = getattr(eng, 'ledger_info', None)
        if li is not None:
            # Step-ledger state: the roofline model in force (peak
            # TFLOP/s, HBM GB/s, analytic FLOPs/token) plus the last
            # committed step's MFU/verdict.
            detail['ledger'] = li()
        return detail

    # -- on-demand device profiler + step-ledger surfaces -------------
    def request_device_profile(self, steps: int) -> dict:
        """Arm a windowed `jax.profiler` capture of the next `steps`
        busy decode ticks (the trainer's SKYTPU_PROFILE_DIR idiom,
        ported to serving).  The capture starts on the next busy step
        — an armed-but-idle replica stays pending — and stops after
        the window (or when work dries up).  Raises
        ProfileActiveError (-> 409) while a window is armed/active."""
        if not self.continuous:
            raise ValueError(
                'device profiling requires continuous batching (the '
                'capture window rides the decode loop); drop '
                '--no-continuous.')
        if not isinstance(steps, int) or isinstance(steps, bool) \
                or steps < 1:
            raise ValueError(
                f'steps must be a positive integer, got {steps!r}')
        profile_dir = os.environ.get('SKYTPU_PROFILE_DIR', '')
        if not profile_dir:
            profile_dir = os.path.join(
                os.environ.get('SKYTPU_LOG_DIR', os.getcwd()),
                'profile')
        with self._profile_lock:
            if self._profile is not None:
                state = ('active' if self._profile.get('active')
                         else 'armed')
                raise ProfileActiveError(
                    f'a device-profile window is already {state} '
                    f"({self._profile['remaining']} steps remaining); "
                    'retry after it completes')
            self._profile = {'remaining': steps, 'dir': profile_dir,
                             'active': False}
        self.events.record('device_profile_armed', steps=steps)
        return {'status': 'armed', 'steps': steps, 'dir': profile_dir}

    def _profile_tick(self, busy: bool) -> None:
        """Decode-loop half of the device profiler: start the trace on
        the first busy step after arming, count busy steps down, stop
        when the window closes (or the engine goes idle mid-window)."""
        import jax
        with self._profile_lock:
            prof = self._profile
            if prof is None:
                return
            if not prof['active']:
                if not busy:
                    return  # armed, waiting for work
                try:
                    jax.profiler.start_trace(prof['dir'])
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception('device-profile start failed')
                    self._profile = None
                    self.events.record('device_profile_failed',
                                       error=repr(e))
                    return
                prof['active'] = True
                self.events.record('device_profile_started',
                                   dir=prof['dir'],
                                   steps=prof['remaining'])
            if busy:
                prof['remaining'] -= 1
            if prof['remaining'] <= 0 or not busy:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception('device-profile stop failed')
                    self.events.record('device_profile_failed',
                                       error=repr(e))
                finally:
                    self._profile = None
                self.events.record('device_profile_done',
                                   dir=prof['dir'])

    def profile_timeline(self, trace_limit: int = 256) -> dict:
        """One Chrome-trace-event JSON joining the step ledger (engine
        steps with MFU/roofline args) and the per-request lifecycle
        rows (utils/timeline.py schema; load into Perfetto)."""
        eng = self.engine
        return ledger_lib.chrome_trace(
            eng.step_ledger.snapshot(),
            eng.traces.recent(trace_limit))

    def _fail_replica(self, error: BaseException) -> None:
        """Terminal: mark unhealthy, stop the loop, fail every waiter
        fast.  The readiness probe (503 /health) stops routing here;
        recovery is a process restart."""
        self._fatal = error
        self._running = False
        self._set_health('unhealthy')
        self.events.record('replica_failed', error=repr(error))
        self.engine.abort(error)

    def _decode_loop(self) -> None:
        """SUPERVISED driver of ContinuousBatchingEngine.step().

        Decodes while any slot is occupied, sleeps on the work event
        when idle.  Handler threads only submit()/wait().  When step()
        raises, the supervisor classifies the failure:

        * transient — abort the in-flight slots (waiters get
          RequestAbortedError immediately), rebuild the engine's device
          state (donated buffers are invalid mid-step), verify the page
          allocator is leak-free, and restart the loop after a short
          jittered backoff.  Queued-but-unadmitted requests survive.
        * fatal (wedged backend, XLA runtime error, page leak) — or
          more than max_restarts transients inside restart_window_s —
          the replica goes unhealthy and stays down.
        """
        restarts = collections.deque()  # monotonic ts of recent restarts
        while self._running:
            try:
                while self._running:
                    self._step_started = time.monotonic()
                    busy = self.engine.step()
                    self._step_started = None
                    if self._profile is not None:
                        self._profile_tick(busy)
                    if not busy:
                        self._work.wait(0.05)
                        self._work.clear()
            except BaseException as e:  # noqa: BLE001 — supervisor sorts it
                self._step_started = None
                if not self._running:
                    break  # shutdown raced the failure; nothing to save
                if failures.classify(e) == failures.FATAL:
                    logger.exception(
                        'decode loop hit a fatal error; marking unhealthy')
                    self._fail_replica(e)
                    return
                now = time.monotonic()
                while restarts and \
                        now - restarts[0] > self.restart_window_s:
                    restarts.popleft()
                restarts.append(now)
                if len(restarts) > self.max_restarts:
                    self._fail_replica(
                        failures.RestartBudgetExceededError(
                            f'{len(restarts)} decode-loop restarts '
                            f'within {self.restart_window_s:.0f}s '
                            f'(budget {self.max_restarts}); last '
                            f'error: {e!r}'))
                    return
                logger.exception(
                    'decode step failed (transient); aborting in-flight '
                    'slots and rebuilding device state')
                try:
                    self.engine.recover(e)
                except BaseException as rec_err:  # noqa: BLE001
                    logger.exception('engine recovery failed')
                    self._fail_replica(rec_err)
                    return
                self._fail_met['restarts'].inc()
                self.events.record('decode_loop_restart',
                                   error=repr(e),
                                   restarts_in_window=len(restarts))
                delay = retry_lib.compute_delay(
                    len(restarts) - 1, base_delay_s=0.05, max_delay_s=2.0)
                if delay > 0:
                    self._work.wait(delay)  # interruptible backoff
                    self._work.clear()

    def _watchdog_loop(self) -> None:
        """Off-thread heartbeat check: a device step that exceeds
        stall_timeout_s (the BackendInitHang class of wedge — the call
        never returns, so the decode loop cannot notice on its own)
        becomes a detected stall.  Waiters fail fast instead of
        blocking out their full deadline on a dead replica."""
        poll = max(0.01, min(self.stall_timeout_s / 4.0, 1.0))
        while not self._stop_evt.wait(poll):
            started = self._step_started
            if started is None:
                continue
            elapsed = time.monotonic() - started
            if elapsed <= self.stall_timeout_s:
                continue
            self._fail_met['stalls'].inc()
            self.events.record('stall_detected',
                               elapsed_s=round(elapsed, 3),
                               timeout_s=self.stall_timeout_s)
            err = failures.StepStallError(
                f'device step exceeded {self.stall_timeout_s:.1f}s '
                f'(running {elapsed:.1f}s); replica presumed wedged')
            logger.error(str(err))
            self._fail_replica(err)
            # If the "stall" was an injected chaos hang, unwind it so
            # the decode thread can observe _running=False and exit.
            chaos.release_hangs()
            return

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    # -- deadlines + load shedding ------------------------------------
    def _deadline_from(self, payload: dict) -> float:
        """Pop the request's `deadline_s` (seconds from now) off the
        payload, defaulting to SKYTPU_REQUEST_DEADLINE_S.  Popped so
        the OpenAI parsers never see the extension key."""
        raw = payload.pop('deadline_s', None)
        if raw is None:
            return self.default_deadline_s
        try:
            deadline_s = float(raw)
        except (TypeError, ValueError):
            raise ValueError(
                f'deadline_s must be a positive number of seconds, '
                f'got {raw!r}') from None
        if deadline_s <= 0:
            raise ValueError(
                f'deadline_s must be > 0, got {deadline_s}')
        return deadline_s

    def _retry_after_s(self) -> int:
        est = self.engine.estimate_queue_wait_s() if self.continuous \
            else 0.0
        return max(1, min(int(est), 60)) if est else 1

    def _admission_check(self, deadline_s: float, n: int = 1) -> None:
        """Shed (raise _Shed -> 503 + Retry-After) instead of admitting
        work that cannot meet its deadline: the client's retry beats a
        queue slot that expires before prefill."""
        if self._draining:
            raise _Shed('server is draining; no new work accepted',
                        reason='draining', retry_after=30)
        if not self.continuous:
            return
        depth = self.engine.queue_depth
        if depth + n > self.max_queue_depth:
            raise _Shed(
                f'queue full ({depth} queued, limit '
                f'{self.max_queue_depth})',
                reason='queue_full', retry_after=self._retry_after_s())
        est = self.engine.estimate_queue_wait_s()
        if est > deadline_s:
            raise _Shed(
                f'estimated queue wait {est:.1f}s exceeds the request '
                f'deadline of {deadline_s:.1f}s',
                reason='deadline_unmeetable',
                retry_after=self._retry_after_s())
        alloc = getattr(self.engine, '_alloc', None)
        if alloc is not None and alloc.free_pages == 0 and \
                depth >= self.engine.n_slots:
            raise _Shed(
                'KV page pool exhausted with a deep admission queue',
                reason='no_free_pages',
                retry_after=self._retry_after_s())

    # -- fleet prefix-cache tier --------------------------------------
    def _prefetch_prefix(self, prompt_ids, peer: Optional[str]) -> None:
        """Fleet tier: before admission, pull the prompt's missing
        prefix pages from the rendezvous owner the router named in
        X-Skytpu-Prefix-Peer into the LOCAL host tier; the engine's
        rehydration walk then turns them into device pages at
        admission, skipping their re-prefill.  Best-effort — any miss,
        timeout, or geometry skew just means a normal prefill — and
        only the locally absent tail of the chain goes on the wire."""
        if not peer or not self.continuous:
            return
        eng = self.engine
        ingest = getattr(eng, 'ingest_prefix_pages', None)
        stats_fn = getattr(eng, 'host_cache_stats', None)
        if ingest is None or stats_fn is None or stats_fn() is None \
                or not eng.page_size:
            return
        from skypilot_tpu.infer import fleet_cache
        from skypilot_tpu.infer import paging as paging_lib
        # Only full pages short of the prompt's last token are ever
        # shareable (the last true token always prefills locally to
        # seed decode) — same cap as the engine's admission path.
        cap = max(0, (len(prompt_ids) - 1) // eng.page_size)
        hashes = paging_lib.chain_hashes(
            prompt_ids, eng.page_size)[:cap]
        hashes = hashes[eng.prefix_resident_run(hashes):]
        if not hashes:
            return
        pages = fleet_cache.fetch_prefix_from_peer(
            peer, hashes, eng._model_name,  # pylint: disable=protected-access
            eng.kv_cache_dtype, eng.page_size)
        if pages:
            ingest(pages)

    # -- graceful drain -----------------------------------------------
    def begin_drain(self, migrate: bool = False,
                    targets=()) -> dict:
        """Stop admission (everything new sheds with 503), let
        in-flight work finish, then shut the server down.  Idempotent;
        /health reports "draining" until exit.

        With ``migrate=True`` and survivor ``targets``, in-flight work
        does NOT have to finish here: the engine checkpoints every
        live decode slot into a SKHO slot artifact at its next step,
        and the request's handler thread relays it to a survivor's
        /handoff — the client's stream continues byte-identical from
        the survivor while this replica exits in seconds instead of
        minutes.  Non-migratable engines (contiguous cache, draft
        model) quietly fall back to the classic finish-local drain."""
        migrating = False
        if migrate:
            tlist = [str(t).strip().rstrip('/') for t in targets
                     if str(t).strip()]
            can = getattr(self.engine, 'can_migrate_out', None)
            if tlist and can is not None and can():
                self._migrate_targets = tlist
                self.engine.request_migrate_out()
                self._work.set()
                migrating = True
        with self._drain_lock:
            first = not self._draining
            self._draining = True
        if first:
            logger.info(
                'drain requested: admission stopped, '
                + ('migrating live slots to '
                   f'{len(self._migrate_targets)} survivor(s)'
                   if migrating else 'waiting for in-flight work'))
            self._set_health('draining')
            self.events.record(
                'drain_begin', migrate=migrating,
                in_flight=self.engine.traces.inflight_count)
            t = threading.Thread(target=self._drain_then_exit,
                                 daemon=True, name='skytpu-drain')
            self._drain_thread = t
            t.start()
        return {'status': 'draining',
                'in_flight': self.engine.traces.inflight_count}

    def _drain_then_exit(self) -> None:
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            if self._fatal is not None:
                break  # replica died mid-drain; nothing left to wait on
            done = self.engine.traces.inflight_count == 0
            if done and self.continuous:
                done = self.engine.is_idle()
            if done:
                # Migrated streams outlive their engine request: the
                # handler thread is still relaying the survivor's
                # tokens to the client.  Exiting now would cut them
                # off mid-stream — wait for parked artifacts to be
                # picked up and every relay to finish.
                with self._relay_lock:
                    relays = self._active_relays
                done = not getattr(self.engine, '_handoffs', None) \
                    and relays == 0
            if done:
                break
            time.sleep(0.05)
        else:
            logger.warning(
                f'drain timed out after {self.drain_timeout_s:.0f}s '
                f'with {self.engine.traces.inflight_count} request(s) '
                'still in flight; shutting down anyway')
        time.sleep(0.2)  # let handler threads flush their responses
        logger.info('drain complete; shutting down')
        self.events.record(
            'drain_complete',
            in_flight=self.engine.traces.inflight_count)
        self.shutdown()

    def _handle_generate(self, payload: dict,
                         http_request_id: Optional[str] = None,
                         trace_parent: Optional[str] = None,
                         decode_target: Optional[str] = None,
                         prefix_peer: Optional[str] = None) -> dict:
        deadline_s = self._deadline_from(payload)
        prompts = payload.get('prompt_ids')
        if not isinstance(prompts, list) or not prompts:
            raise ValueError('prompt_ids must be a non-empty list of '
                             'token-id lists')
        sampling = engine_lib.SamplingConfig(
            temperature=float(payload.get('temperature', 0.0)),
            top_k=int(payload.get('top_k', 0)),
            top_p=float(payload.get('top_p', 1.0)),
            eos_id=payload.get('eos_id'),
            max_new_tokens=int(payload.get('max_new_tokens', 64)),
            seed=(int(payload['seed'])
                  if payload.get('seed') is not None else None))
        self._admission_check(deadline_s, n=len(prompts))
        if prefix_peer:
            for p in prompts:
                self._prefetch_prefix(p, prefix_peer)
        if self.continuous:
            # All-or-nothing: a rejected prompt (e.g. overlong) must
            # not strand its siblings decoding with no reader.
            rids = []
            try:
                for p in prompts:
                    rid = self.engine.submit(
                        p, sampling, deadline_s=deadline_s,
                        http_request_id=http_request_id,
                        trace_parent=trace_parent)
                    rids.append(rid)
                self._work.set()
                # No explicit timeout: wait() derives it from the
                # request's own deadline.
                tokens = [self.engine.wait(r) for r in rids]
                tokens = [
                    self._relay_blocking(r, t, decode_target,
                                         http_request_id,
                                         deadline_s=deadline_s)
                    for r, t in zip(rids, tokens)]
            except BaseException:
                for r in rids:
                    self.engine.cancel(r)
                raise
            return {'tokens': tokens}
        with self._lock:
            tokens = self.engine.generate(
                prompts, sampling, http_request_id=http_request_id,
                trace_parent=trace_parent)
        return {'tokens': tokens}

    # -- disaggregated serving ----------------------------------------
    def _handle_handoff(self, blob: bytes, handler) -> None:
        """POST /handoff (decode-role side): admit a prefill replica's
        KV artifact and stream the decoded tokens back as ndjson — one
        ``{"token": t}`` line per committed token, then
        ``{"done": true}``.  The body is the binary artifact and is
        never JSON-parsed; geometry/version validation happens inside
        admit_handoff BEFORE any engine state is touched, so a bad
        artifact is a clean 400/409."""
        hdr = handler.headers.get(DEADLINE_HEADER)
        try:
            deadline_s = float(hdr) if hdr else self.default_deadline_s
        except (TypeError, ValueError):
            deadline_s = self.default_deadline_s
        if deadline_s <= 0:
            deadline_s = self.default_deadline_s
        self._admission_check(deadline_s)
        rid = self.engine.admit_handoff(
            blob, stream=True, deadline_s=deadline_s,
            http_request_id=handler.request_id,
            trace_parent=handler.trace_parent)
        self._work.set()
        handler.send_response(200)
        handler.send_header('Content-Type', 'application/x-ndjson')
        handler.end_headers()

        def _line(obj) -> None:
            handler.wfile.write((json.dumps(obj) + '\n').encode())
            handler.wfile.flush()

        try:
            for tok in self.engine.stream(
                    rid, timeout=self.stream_token_timeout):
                _line({'token': tok})
            # Chained migration: if a migrate-drain checkpointed THIS
            # admitted slot too, relay the artifact onward and keep
            # streaming — the upstream relay never notices.
            with self._relay_lock:
                self._active_relays += 1
            try:
                blob = self.engine.take_handoff(rid)
                if blob is not None:
                    for tok in self._relay_handoff(
                            blob, handler.request_id, None,
                            deadline_s=deadline_s):
                        _line({'token': tok})
            finally:
                with self._relay_lock:
                    self._active_relays -= 1
            _line({'done': True})
        except TimeoutError:
            self.engine.cancel(rid)
            try:
                _line({'error': 'inter-token timeout: decode stalled'})
            except OSError:
                pass
        except (BrokenPipeError, ConnectionError, OSError):
            # The prefill relay went away mid-stream: release the slot
            # so it stops decoding for nobody.
            self.engine.cancel(rid)
        finally:
            # ndjson body is delimited by connection close (same
            # framing as the SSE path — no Content-Length).
            handler.close_connection = True

    def _relay_handoff(self, blob: bytes,
                       http_request_id: Optional[str],
                       decode_target: Optional[str],
                       deadline_s: Optional[float] = None
                       ) -> Iterator[int]:
        """Prefill-role side: ship the artifact to a decode replica and
        yield the tokens it streams back.  The router's per-request
        X-Skytpu-Decode-Target pick is tried first, then the static
        --decode-peers list; a peer that refuses the CONNECTION (shed,
        down) moves on to the next — the artifact is immutable bytes,
        so resending is safe.  Once tokens flow, failures propagate:
        replaying a partially-consumed stream would duplicate output.

        A migrate-drain's slot artifacts travel the same path: the
        drain's survivor targets join the candidate list, and the
        survivor's /handoff resumes the slot mid-generation."""
        targets = []
        if decode_target:
            targets.append(decode_target.rstrip('/'))
        targets.extend(t for t in self._decode_peers
                       if t not in targets)
        targets.extend(t for t in self._migrate_targets
                       if t not in targets)
        if not targets:
            raise RuntimeError(
                'no replica to hand off to: the router did not stamp '
                + handoff_lib.DECODE_TARGET_HEADER + ', --decode-peers '
                'is empty, and no migrate-drain named survivors')
        last: Optional[BaseException] = None
        for target in targets:
            req = urllib.request.Request(target + '/handoff',
                                         data=blob, method='POST')
            req.add_header('Content-Type', 'application/octet-stream')
            if http_request_id:
                req.add_header('X-Request-Id', http_request_id)
            if deadline_s is not None and deadline_s > 0:
                # The decode replica runs its own admission check;
                # without the deadline it falls back to its default
                # and a tight-SLO request loses its budget mid-relay.
                req.add_header(DEADLINE_HEADER, f'{deadline_s:g}')
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.stream_token_timeout)
            except urllib.error.HTTPError as e:
                # Must come before URLError (its base class): the
                # generic arm below retries on the next peer, and a
                # fail-closed status (wire-version/format conflict)
                # would fail identically everywhere — or worse,
                # half-succeed and duplicate output.
                if e.code in HANDOFF_FAIL_CLOSED:
                    raise RuntimeError(
                        f'decode target {target} rejected the '
                        f'handoff with HTTP {e.code}; fail-closed, '
                        f'not retrying') from e
                logger.warning(
                    f'decode target {target} answered HTTP {e.code} '
                    f'to a handoff; trying the next peer')
                last = e
                continue
            except (urllib.error.URLError, OSError) as e:
                logger.warning(
                    f'decode target {target} refused a handoff '
                    f'({e!r}); trying the next peer')
                last = e
                continue
            try:
                for raw in resp:
                    msg = json.loads(raw)
                    if 'token' in msg:
                        yield msg['token']
                    elif msg.get('done'):
                        return
                    else:
                        raise RuntimeError(
                            'decode replica failed mid-handoff: '
                            f'{msg.get("error", msg)}')
                raise RuntimeError('decode replica closed the handoff '
                                   'stream before done')
            finally:
                resp.close()
        raise RuntimeError(
            f'no decode replica accepted the handoff (tried '
            f'{len(targets)} target(s)); last error: {last!r}')

    def _token_iter(self, rid: int,
                    decode_target: Optional[str] = None,
                    http_request_id: Optional[str] = None,
                    deadline_s: Optional[float] = None
                    ) -> Iterator[int]:
        """Unified per-token stream for one request: the local engine's
        stream, then — iff the engine handed the request off (prefill
        role after its seed token, OR any role whose slot a
        migrate-drain checkpointed) — the remote replica's relayed
        tail.  Callers cannot tell disaggregated or migrated serving
        from local decode (the early tokens come from the local
        stream, the rest from the wire)."""
        for tok in self.engine.stream(
                rid, timeout=self.stream_token_timeout):
            yield tok
        # Count the relay BEFORE popping the artifact: between the two,
        # a drain poll must still see work in flight.
        with self._relay_lock:
            self._active_relays += 1
        try:
            blob = self.engine.take_handoff(rid)
            if blob is None:
                return  # finished locally
            yield from self._relay_handoff(blob, http_request_id,
                                           decode_target,
                                           deadline_s=deadline_s)
        finally:
            with self._relay_lock:
                self._active_relays -= 1

    def _relay_blocking(self, rid: int, toks: list,
                        decode_target: Optional[str],
                        http_request_id: Optional[str],
                        deadline_s: Optional[float] = None) -> list:
        """Blocking-route tail of the handoff: append the remote
        replica's tokens to the locally produced ones (prefill role's
        seed token, or a migrated slot's pre-migration output)."""
        with self._relay_lock:
            self._active_relays += 1
        try:
            blob = self.engine.take_handoff(rid)
            if blob is None:
                return toks
            return toks + list(self._relay_handoff(
                blob, http_request_id, decode_target,
                deadline_s=deadline_s))
        finally:
            with self._relay_lock:
                self._active_relays -= 1

    # -- OpenAI-compatible surface ------------------------------------
    def _sampling_for(self, req) -> 'engine_lib.SamplingConfig':
        return engine_lib.SamplingConfig(
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, eos_id=self.tokenizer.eos_id,
            max_new_tokens=req.max_tokens, seed=req.seed)

    def _openai_blocking(self, req, prompt_ids,
                         http_request_id: Optional[str] = None,
                         deadline_s: Optional[float] = None,
                         trace_parent: Optional[str] = None,
                         decode_target: Optional[str] = None) -> dict:
        from skypilot_tpu.infer import openai_api
        sampling = self._sampling_for(req)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if self.continuous:
            rid = self.engine.submit(prompt_ids, sampling,
                                     deadline_s=deadline_s,
                                     http_request_id=http_request_id,
                                     trace_parent=trace_parent)
            self._work.set()
            toks = self.engine.wait(rid)
            toks = self._relay_blocking(rid, toks, decode_target,
                                        http_request_id,
                                        deadline_s=deadline_s)
        else:
            with self._lock:
                toks = self.engine.generate(
                    [prompt_ids], sampling,
                    http_request_id=http_request_id,
                    trace_parent=trace_parent)[0]
        eos = self.tokenizer.eos_id
        eos_hit = bool(toks) and eos is not None and toks[-1] == eos
        scanner = openai_api.StopScanner(req.stop)
        text = scanner.feed(self.tokenizer.decode(toks))
        text += scanner.flush()
        finish = 'stop' if (eos_hit or scanner.hit) else 'length'
        return openai_api.completion_response(
            req, text, finish, prompt_tokens=len(prompt_ids),
            completion_tokens=len(toks))

    def _openai_stream(self, req, prompt_ids, handler,
                       deadline_s: Optional[float] = None) -> None:
        """SSE: one `data:` event per decoded text fragment, riding
        the engine's per-token stream queue; ends with the
        finish_reason chunk and `data: [DONE]`."""
        from skypilot_tpu.infer import openai_api
        from skypilot_tpu.infer import tokenizer as tokenizer_lib
        sampling = self._sampling_for(req)
        http_rid = getattr(handler, 'request_id', None)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        rid = self.engine.submit(
            prompt_ids, sampling, stream=True, deadline_s=deadline_s,
            http_request_id=http_rid,
            trace_parent=getattr(handler, 'trace_parent', None))
        self._work.set()

        def _sse(obj) -> None:
            handler.wfile.write(
                f'data: {json.dumps(obj)}\n\n'.encode())
            handler.wfile.flush()

        def _sse_error(message: str) -> None:
            """Mid-stream failure with a live client: an error event
            + [DONE] is the only legal framing (a second HTTP status
            line would be protocol garbage).  Carries the request id so
            the client can quote it back at the server logs/traces."""
            try:
                _sse({'error': {
                    'message': message, 'type': 'server_error',
                    'param': None, 'code': None,
                    'request_id': http_rid}})
                handler.wfile.write(b'data: [DONE]\n\n')
                handler.wfile.flush()
            except OSError:
                pass
            handler.close_connection = True

        decoder = tokenizer_lib.IncrementalDecoder(self.tokenizer)
        scanner = openai_api.StopScanner(req.stop)
        eos = self.tokenizer.eos_id
        n_tokens = 0
        eos_hit = False
        started = False
        try:
            handler.send_response(200)
            handler.send_header('Content-Type', 'text/event-stream')
            handler.send_header('Cache-Control', 'no-cache')
            handler.end_headers()
            started = True
            if req.chat:  # role announcement first
                _sse(openai_api.stream_chunk(req, None, first=True))
            for tok in self._token_iter(
                    rid,
                    decode_target=getattr(handler, 'decode_target',
                                          None),
                    http_request_id=http_rid,
                    deadline_s=deadline_s):
                if chaos.should_inject('client_disconnect'):
                    raise BrokenPipeError(
                        'chaos: simulated client disconnect')
                n_tokens += 1
                if eos is not None and tok == eos:
                    eos_hit = True
                    continue  # engine completes after eos
                piece = decoder.feed(tok)
                if not piece:
                    continue
                out = scanner.feed(piece)
                if out:
                    _sse(openai_api.stream_chunk(req, out))
                if scanner.hit:
                    self.engine.cancel(rid)
                    break
            tail = decoder.flush()
            out = (scanner.feed(tail) if tail else '') + \
                scanner.flush()
            if out:
                _sse(openai_api.stream_chunk(req, out))
            finish = 'stop' if (eos_hit or scanner.hit) else (
                'length' if n_tokens >= req.max_tokens else 'stop')
            _sse(openai_api.stream_chunk(req, None,
                                         finish_reason=finish))
            handler.wfile.write(b'data: [DONE]\n\n')
            handler.wfile.flush()
        except TimeoutError:
            # Decode stalled past the inter-token bound; stream()
            # already canceled the request.  MUST precede the OSError
            # arm (TimeoutError subclasses it) — the client is still
            # connected and deserves an error event, and the stall
            # must be visible server-side.
            logger.warning(
                f'stream {req.oai_id}: no token within '
                f'{self.stream_token_timeout:.0f}s; terminating SSE')
            self.engine.cancel(rid)
            _sse_error('inter-token timeout: decode stalled')
        except (BrokenPipeError, ConnectionError, OSError):
            # Client went away mid-stream: release the slot so it
            # stops decoding for nobody (also covers a disconnect
            # during header send, before any event went out).
            self.engine.cancel(rid)
            handler.close_connection = True
        except Exception as e:  # pylint: disable=broad-except
            logger.exception(f'stream {req.oai_id} failed mid-flight')
            self.engine.cancel(rid)
            if started:
                _sse_error(f'stream failed: {e}')
            else:
                raise  # headers not sent; do_POST replies cleanly

    def _handle_openai(self, payload: dict, chat: bool,
                       handler) -> Optional[dict]:
        """Returns a JSON body to reply with, or None if the handler
        already streamed the response itself."""
        from skypilot_tpu.infer import openai_api
        deadline_s = self._deadline_from(payload)
        parse = openai_api.parse_chat_request if chat else \
            openai_api.parse_completion_request
        req = parse(payload, self.model_name)
        prompt_ids = self.tokenizer.encode(req.prompt_text)
        if not prompt_ids:
            raise openai_api.OpenAIError(
                'prompt encodes to zero tokens')
        # Shed before any work (and before SSE headers go out on the
        # stream path — a 503 must still be expressible).
        self._admission_check(deadline_s)
        self._prefetch_prefix(prompt_ids,
                              getattr(handler, 'prefix_peer', None))
        if req.stream:
            if not self.continuous:
                raise openai_api.OpenAIError(
                    'stream=true requires continuous batching '
                    '(server started with --no-continuous)')
            self._openai_stream(req, prompt_ids, handler, deadline_s)
            return None
        return self._openai_blocking(
            req, prompt_ids, getattr(handler, 'request_id', None),
            deadline_s,
            trace_parent=getattr(handler, 'trace_parent', None),
            decode_target=getattr(handler, 'decode_target', None))

    def serve_forever(self) -> None:
        self.start()
        assert self._server is not None
        logger.info(f'inference server on :{self.port}')
        # 50ms poll: shutdown()/drain block on the serve loop noticing.
        self._server.serve_forever(poll_interval=0.05)

    def start(self) -> None:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):

            request_id = '-'
            _last_code = 0

            def log_message(self, format, *args):  # noqa: A002
                # Access logs on the framework logger at DEBUG (JSON
                # when SKYTPU_LOG_JSON=1), stamped with the request id
                # — BaseHTTPRequestHandler would write raw stderr.
                logger.debug(f'{self.address_string()} '
                             f'[{self.request_id}] {format % args}')

            def send_response(self, code, message=None):
                super().send_response(code, message)
                self.send_header('X-Request-Id', self.request_id)
                self._last_code = code

            def _reply(self, code: int, body: dict,
                       allow: Optional[str] = None,
                       retry_after: Optional[int] = None) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(data)))
                if allow is not None:
                    self.send_header('Allow', allow)
                if retry_after is not None:
                    self.send_header('Retry-After', str(retry_after))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                self._dispatch('GET')

            def do_POST(self):  # noqa: N802
                self._dispatch('POST')

            def _dispatch(self, method: str) -> None:
                incoming = self.headers.get('X-Request-Id', '')
                self.request_id = (
                    incoming if _REQUEST_ID_RE.match(incoming)
                    else 'req-' + uuid.uuid4().hex[:16])
                # Distributed-trace context: the router stamps
                # X-Skytpu-Trace on forwarded attempts; the parent half
                # lands on the engine trace so a stitched trace can
                # join the router's attempt span to this replica's
                # per-request lifecycle.
                self.trace_parent = None
                ctx = tracing_lib.parse_trace_context(
                    self.headers.get(tracing_lib.TRACE_HEADER))
                if ctx is not None:
                    self.trace_parent = ctx[1]
                # Router-picked decode replica for this request (only
                # meaningful on a prefill-role replica).
                self.decode_target = self.headers.get(
                    handoff_lib.DECODE_TARGET_HEADER)
                # Rendezvous owner of this prompt's prefix (stamped by
                # the router when it had to route AWAY from the owner);
                # admission pre-fetches the prefix pages from it.
                self.prefix_peer = self.headers.get(
                    handoff_lib.PREFIX_PEER_HEADER)
                self._last_code = 0
                route = self.path.split('?', 1)[0]
                known = route in _GET_ROUTES or route in _POST_ROUTES
                label = route if known else 'other'
                met = outer._http_met  # pylint: disable=protected-access
                start = time.perf_counter()
                try:
                    if method == 'GET':
                        self._do_get(route)
                    else:
                        self._do_post(route)
                finally:
                    met['latency'].labels(
                        method=method, route=label).observe(
                            time.perf_counter() - start)
                    met['requests'].labels(
                        method=method, route=label,
                        code=str(self._last_code or 0)).inc()

            def _do_get(self, route: str) -> None:
                if route == '/health':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    verbose = query.get('verbose', ['0'])[0] \
                        not in ('0', '', 'false')
                    detail = outer.health_detail() if verbose else {}
                    if outer._fatal is not None:  # pylint: disable=protected-access
                        self._reply(503, {
                            'status': 'unhealthy',
                            'error': repr(outer._fatal),  # pylint: disable=protected-access
                            **detail})
                    elif outer._draining:  # pylint: disable=protected-access
                        # 503 so the router stops sending traffic while
                        # in-flight work finishes.
                        self._reply(503, {'status': 'draining',
                                          **detail})
                    else:
                        self._reply(200, {'status': 'ok', **detail})
                elif route == '/v1/models':
                    self._reply(200, {
                        'object': 'list',
                        'data': [{'id': outer.model_name,
                                  'object': 'model',
                                  'created': 0,
                                  'owned_by': 'skypilot-tpu'}]})
                elif route == '/metrics':
                    # Scrape-time watermarks (peak pages / device
                    # memory) — polled here, not per step, so the
                    # publish-overhead contract is untouched.
                    outer.engine.publish_memory_watermarks()
                    data = outer.registry.expose().encode()
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     metrics_lib.CONTENT_TYPE_LATEST)
                    self.send_header('Content-Length', str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif route == '/traces':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        limit = int(query.get('limit', ['100'])[0])
                    except ValueError:
                        limit = 100
                    store = outer.engine.traces
                    want = query.get('request_id', [None])[0]
                    if want is not None:
                        # Stitch support: the router looks up replica
                        # traces by the EXTERNAL id it forwarded, which
                        # lands on http_request_id (the engine rid is
                        # replica-local).
                        traces = [
                            t for t in store.recent(100000)
                            if t.get('http_request_id') == want
                        ][:limit]
                    else:
                        traces = store.recent(limit)
                    self._reply(200, {
                        'traces': traces,
                        'in_flight': store.inflight_count})
                elif route == '/events':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        limit = int(query.get('limit', ['100'])[0])
                    except ValueError:
                        limit = 100
                    self._reply(200, {
                        'events': outer.events.snapshot(limit)})
                elif route == '/profile/steps':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        limit = int(query.get('limit', ['128'])[0])
                    except ValueError:
                        limit = 128
                    eng = outer.engine
                    self._reply(200, {
                        'steps': eng.step_ledger.snapshot(limit),
                        'info': eng.ledger_info(),
                        'summary': eng.step_ledger.summary()})
                elif route == '/profile/timeline':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        trace_limit = int(
                            query.get('traces', ['256'])[0])
                    except ValueError:
                        trace_limit = 256
                    self._reply(200,
                                outer.profile_timeline(trace_limit))
                elif route == '/kv_prefix':
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    raw = query.get('hashes', [''])[0]
                    try:
                        hashes = [int(h) for h in raw.split(',')
                                  if h.strip()]
                    except ValueError:
                        self._reply(400, {
                            'error': 'hashes must be comma-separated '
                                     'integers'})
                        return
                    blob_fn = getattr(outer.engine, 'kv_prefix_blob',
                                      None)
                    blob = blob_fn(hashes) if blob_fn is not None \
                        and hashes else None
                    if blob is None:
                        self._reply(404, {
                            'error': 'no host-tier pages for this '
                                     'chain on this replica'})
                        return
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     'application/octet-stream')
                    self.send_header('Content-Length', str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                elif route in _POST_ROUTES:
                    self._reply(405, {'error': 'method not allowed'},
                                allow='POST')
                else:
                    self._reply(404, {'error': 'not found'})

            def _do_post(self, route: str) -> None:
                from skypilot_tpu.infer import openai_api
                if route not in _POST_ROUTES:
                    if route in _GET_ROUTES:
                        self._reply(405,
                                    {'error': 'method not allowed'},
                                    allow='GET')
                    else:
                        self._reply(404, {'error': 'not found'})
                    return
                try:
                    length = int(self.headers.get('Content-Length', 0))
                    if route == '/handoff':
                        # Binary artifact body — MUST NOT hit the JSON
                        # parse below.
                        outer._handle_handoff(  # pylint: disable=protected-access
                            self.rfile.read(length), self)
                        return
                    payload = json.loads(self.rfile.read(length) or b'{}')
                    if route == '/drain':
                        self._reply(200, outer.begin_drain(
                            migrate=bool(payload.get('migrate')),
                            targets=payload.get('targets') or ()))
                        return
                    if route == '/profile/device':
                        self._reply(200, outer.request_device_profile(
                            payload.get('steps', 8)))
                        return
                    if route == '/generate':
                        self._reply(200, outer._handle_generate(  # pylint: disable=protected-access
                            payload, self.request_id,
                            trace_parent=self.trace_parent,
                            decode_target=self.decode_target))
                        return
                    body = outer._handle_openai(  # pylint: disable=protected-access
                        payload, chat=route.endswith(
                            '/chat/completions'), handler=self)
                    if body is not None:
                        self._reply(200, body)
                except _Shed as e:
                    outer._fail_met['shed'].labels(  # pylint: disable=protected-access
                        reason=e.reason).inc()
                    self._reply(503, {'error': str(e),
                                      'reason': e.reason},
                                retry_after=e.retry_after)
                # Handoff errors subclass ValueError: these arms must
                # precede the generic ValueError arm below.  409 for
                # version skew (mixed fleet mid-rollout retries
                # elsewhere), 400 for a malformed/incompatible
                # artifact.
                except handoff_lib.HandoffVersionError as e:
                    self._reply(409, {'error': str(e)})
                except ProfileActiveError as e:
                    # Device capture is single-flight: a second arm
                    # while one is pending/running conflicts (409).
                    self._reply(409, {'error': str(e)})
                except handoff_lib.HandoffFormatError as e:
                    self._reply(400, {'error': str(e)})
                except openai_api.OpenAIError as e:
                    self._reply(e.status, e.body())
                except TimeoutError as e:
                    # Includes failures.DeadlineExceededError: the
                    # request missed its deadline (queued too long or
                    # decode too slow) — a gateway-timeout, not a 500.
                    self._reply(504, {'error': str(e)})
                except ValueError as e:
                    if route in ('/generate', '/profile/device'):
                        self._reply(400, {'error': str(e)})
                    else:
                        self._reply(
                            400, openai_api.OpenAIError(str(e)).body())
                except Exception as e:  # pylint: disable=broad-except
                    logger.exception('generate failed')
                    self._reply(500, {'error': str(e)})

        self._server = _HTTPServer((self._host, self._port), Handler)
        if self.continuous and self._decode_thread is None:
            self._running = True
            self._decode_thread = threading.Thread(
                target=self._decode_loop, daemon=True,
                name='skytpu-decode-loop')
            self._decode_thread.start()
            if self.stall_timeout_s > 0 and \
                    self._watchdog_thread is None:
                self._watchdog_thread = threading.Thread(
                    target=self._watchdog_loop, daemon=True,
                    name='skytpu-step-watchdog')
                self._watchdog_thread.start()

    def shutdown(self) -> None:
        # Flip the run flag and wake the decode loop BEFORE joining it
        # (joining first would deadlock a loop parked on the work
        # event until its 50ms poll fired).
        self._running = False
        self._stop_evt.set()
        self._work.set()
        chaos.release_hangs()
        if self._decode_thread is not None:
            self._decode_thread.join(timeout=self.shutdown_join_s)
            if self._decode_thread.is_alive():
                # A hung device step cannot be interrupted from Python;
                # the thread is a daemon, so leaking it is survivable —
                # but say so instead of silently pretending it joined.
                logger.warning(
                    f'decode thread still alive after '
                    f'{self.shutdown_join_s:.1f}s join timeout '
                    '(likely a hung device step); leaking the daemon '
                    'thread')
            self._decode_thread = None
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=self.shutdown_join_s)
            self._watchdog_thread = None
        # Fence the engine's async pipeline: after the decode loop is
        # down nothing will consume an in-flight step, so join the
        # fetch thread too (no-op for sync/request-level engines).
        close = getattr(getattr(self, 'engine', None), 'close', None)
        if close is not None:
            close(timeout=self.shutdown_join_s)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--port', type=int, default=8000)
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--max-batch-size', type=int, default=4)
    parser.add_argument('--max-seq-len', type=int, default=None)
    parser.add_argument('--checkpoint-dir', default=None,
                        help='trainer Orbax checkpoint to serve '
                             '(bucket-mounted path)')
    parser.add_argument('--mesh', default=None,
                        help="shard over local devices, e.g. "
                             "'tensor=4': params AND the paged KV "
                             'pool split on the kv-head axis (page-/'
                             'sequence-sharded fallback when kv-heads '
                             "don't divide, e.g. DeepSeek MLA); "
                             'composes with --page-size/'
                             '--kv-cache-dtype/--spec-k/'
                             '--decode-kernel. Greedy output is '
                             'bit-identical to unsharded serving.')
    parser.add_argument('--no-continuous', dest='continuous',
                        action='store_false', default=True,
                        help='Request-level batching instead of '
                             'continuous (slot-based) batching.')
    parser.add_argument('--prefill-chunk', type=int, default=0,
                        help='Chunked prefill: process long prompts '
                             'this many tokens per decode tick so live '
                             'requests keep generating (0 = whole '
                             'prompt at admission).')
    parser.add_argument('--quantize', default=None,
                        choices=['int8'],
                        help='Weight-only int8 serving: halves param '
                             'HBM traffic; composes with --mesh '
                             '(q8/scale leaves shard like their float '
                             'kernels).')
    parser.add_argument('--kv-cache-dtype', default='auto',
                        choices=['auto', 'int8'],
                        help='KV-cache storage dtype: int8 stores '
                             'cache rows quantized with per-(kv-head, '
                             'position) f32 absmax scales — halves '
                             'decode cache HBM traffic vs bf16 and '
                             'doubles the contexts that fit; dequant '
                             'stays fused in the attention epilogue. '
                             'Composes with --quantize (weights).')
    parser.add_argument('--page-size', type=int, default=0,
                        help='Paged KV cache: split the cache into '
                             'pages of this many positions (power of '
                             'two dividing --max-seq-len and the '
                             'prefill bucket) — decode HBM reads '
                             'track each request\'s LIVE context '
                             'instead of max-seq-len, and requests '
                             'sharing a prompt prefix share its '
                             'pages (prefilled once, refcounted). '
                             '0 = contiguous per-slot rows. Requires '
                             'continuous batching.')
    parser.add_argument('--max-pages', type=int, default=0,
                        help='Page-pool size for --page-size (incl. '
                             'the reserved null page). Default sizes '
                             'the pool so every slot can fill its '
                             'row; smaller values oversubscribe — '
                             'admission then waits for free pages '
                             '(backpressure) instead of free slots.')
    parser.add_argument('--compilation-cache-dir', default=None,
                        help='Persistent XLA compile cache: '
                             'scale-up replicas/restarts skip the '
                             'prefill+decode compiles and come '
                             'READY in seconds.')
    parser.add_argument('--platform', default=None,
                        help="Force a jax platform (e.g. 'cpu' for "
                             'tests; env JAX_PLATFORMS alone is not '
                             'enough on tunneled-TPU hosts).')
    parser.add_argument('--tokenizer', default=None,
                        help='HF tokenizer name for the /v1 text API; '
                             "default 'byte' (built-in UTF-8 byte "
                             'tokenizer, test/dev models).')
    parser.add_argument('--allow-random-weights', action='store_true',
                        default=False,
                        help='Serve without a checkpoint (randomly '
                             'initialized weights). Tests/dev only; '
                             'without this flag the server refuses '
                             'to start paramless.')
    parser.add_argument('--served-model-name', default=None,
                        help='Model id reported by /v1/models and in '
                             'OpenAI responses (default: --model).')
    parser.add_argument('--model-overrides', default=None,
                        help='JSON dict of model-config overrides '
                             '(e.g. \'{"n_layers": 2, "dim": 64}\') — '
                             'lets subprocess test replicas run tiny '
                             'geometry without a bespoke model name.')
    parser.add_argument('--draft-model', default=None,
                        help='Speculative decoding draft model: a '
                             'small model (same tokenizer family — '
                             'vocab checked at init) that proposes '
                             '--spec-k tokens per decode step; the '
                             'target verifies all of them in one '
                             'multi-token forward and commits the '
                             'accepted prefix. Output is unchanged: '
                             'greedy requests stay bit-identical, '
                             'sampled requests keep their exact '
                             'distribution (rejection sampling). '
                             'Requires --spec-k.')
    parser.add_argument('--draft-checkpoint-dir', default=None,
                        help='Checkpoint for --draft-model (random '
                             'init without it — tests/dev only).')
    parser.add_argument('--draft-overrides', default=None,
                        help='JSON dict of draft-model config '
                             'overrides (like --model-overrides).')
    parser.add_argument('--spec-k', type=int, default=0,
                        help='Speculative tokens proposed per decode '
                             'step (0 disables speculation). Without '
                             '--draft-model, proposals come from '
                             'n-gram prompt-lookup self-drafting: '
                             'zero extra weights, wins on repetitive '
                             '/ shared-prefix traffic.')
    parser.add_argument('--async-pipeline', dest='async_pipeline',
                        action='store_true', default=True,
                        help='Double-buffered decode stepping: '
                             'dispatch step N+1 while step N\'s '
                             'tokens are fetched/committed, hiding '
                             'host scheduling behind device '
                             'execution. Greedy output stays '
                             'bit-identical to the synchronous loop. '
                             'Default on.')
    parser.add_argument('--no-async-pipeline', dest='async_pipeline',
                        action='store_false',
                        help='Escape hatch: run the fully '
                             'synchronous decode loop (dispatch, '
                             'fetch, commit inline each tick).')
    parser.add_argument('--decode-kernel', default='auto',
                        choices=['auto', 'fused', 'xla'],
                        help='Paged decode-attention implementation: '
                             "'fused' walks the block table inside a "
                             'Pallas kernel (page gather + int8 '
                             'dequant + grouped attention + verify '
                             'windows in one kernel, zero gather '
                             "round-trip); 'xla' is the gather_pages "
                             '+ grouped-einsum path (permanent '
                             "fallback and parity oracle). 'auto' "
                             'picks fused on TPU with --page-size, '
                             'xla otherwise — off-TPU the fused '
                             'kernel only runs interpreted (tests).')
    parser.add_argument('--prefill-kernel', default='auto',
                        choices=['auto', 'fused', 'xla'],
                        help='Chunked-prefill attention '
                             "implementation: 'fused' walks the "
                             'paged cache prefix inside the ragged-'
                             'prefill Pallas kernel (online-softmax '
                             'tiling, int8 dequant, cursor-base '
                             'causal masking, zero gathered '
                             "intermediates); 'xla' is the sliced-"
                             'prefix + grouped-einsum path (permanent '
                             "fallback and parity oracle). 'auto' "
                             'picks fused on TPU with --page-size, '
                             'xla otherwise.')
    parser.add_argument('--prefill-mix-budget', type=int, default=0,
                        help='Mixed prefill/decode batching: admit up '
                             'to this many prompt-chunk tokens into '
                             'each decode step so long prompts '
                             'amortize across steps instead of '
                             'stalling co-resident decodes (0 = '
                             'dedicated prefill ticks, today\'s '
                             'behavior). Composes with --spec-k, '
                             '--page-size, --mesh and the async '
                             'pipeline.')
    parser.add_argument('--role', default='both',
                        choices=['both', 'prefill', 'decode'],
                        help='Disaggregated serving role. One binary, '
                             "three modes: 'both' (default) serves "
                             "prefill+decode as today; 'prefill' runs "
                             'chunked prefill at full batch width, '
                             'then hands each request to a decode '
                             'replica as a KV page artifact (POST '
                             "/handoff) and relays its tokens; "
                             "'decode' accepts /handoff artifacts "
                             'mid-stream (deduped against its prefix '
                             'cache by page id) and decodes them. '
                             'Greedy output across a handoff is '
                             'bit-identical to --role both.')
    parser.add_argument('--host-cache-mb', type=int, default=0,
                        help='Host-RAM prefix-cache tier budget in '
                             'MiB (0 disables). With --page-size, '
                             'reclaimable prefix pages the allocator '
                             'would cannibalise spill here and later '
                             'prefix hits rehydrate the device page '
                             'instead of re-prefilling; GET '
                             '/kv_prefix serves the tier to fleet '
                             'peers and migrate-drains ride the same '
                             'machinery.')
    parser.add_argument('--decode-peers', default=None,
                        help='Comma-separated decode-replica base URLs '
                             'a --role prefill replica may hand off '
                             'to when the router did not stamp a '
                             'per-request target (static fleets, '
                             'tests).')
    parser.add_argument('--kv-read-bucket', type=int, default=512,
                        help='Decode attention reads only the live '
                             'cache prefix, rounded up to this bucket '
                             '(one compile per bucket crossed; big HBM '
                             'savings at long max-seq-len). 0 reads '
                             'the full cache and compiles decode '
                             'exactly once.')
    args = parser.parse_args()
    if args.platform:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh_lib.force_platform_and_touch(args.platform)
    overrides = None
    if args.model_overrides:
        overrides = json.loads(args.model_overrides)
        if not isinstance(overrides, dict):
            parser.error('--model-overrides must be a JSON object')
    draft_overrides = None
    if args.draft_overrides:
        draft_overrides = json.loads(args.draft_overrides)
        if not isinstance(draft_overrides, dict):
            parser.error('--draft-overrides must be a JSON object')
    if args.draft_model and not args.spec_k:
        parser.error('--draft-model requires --spec-k > 0')
    InferenceServer(model=args.model, port=args.port, host=args.host,
                    model_overrides=overrides,
                    max_batch_size=args.max_batch_size,
                    max_seq_len=args.max_seq_len,
                    checkpoint_dir=args.checkpoint_dir,
                    mesh_config=args.mesh,
                    continuous=args.continuous,
                    prefill_chunk=args.prefill_chunk,
                    kv_read_bucket=args.kv_read_bucket,
                    quantize=args.quantize,
                    kv_cache_dtype=args.kv_cache_dtype,
                    page_size=args.page_size,
                    max_pages=args.max_pages,
                    compilation_cache_dir=args.compilation_cache_dir,
                    tokenizer=args.tokenizer,
                    allow_random_weights=args.allow_random_weights,
                    served_model_name=args.served_model_name,
                    draft_model=args.draft_model,
                    draft_checkpoint_dir=args.draft_checkpoint_dir,
                    draft_overrides=draft_overrides,
                    spec_k=args.spec_k,
                    decode_kernel=args.decode_kernel,
                    prefill_kernel=args.prefill_kernel,
                    prefill_mix_budget=args.prefill_mix_budget,
                    async_pipeline=args.async_pipeline,
                    role=args.role,
                    decode_peers=args.decode_peers,
                    host_cache_bytes=args.host_cache_mb << 20,
                    ).serve_forever()


if __name__ == '__main__':
    main()
