"""The lifecycle pipeline: OPTIMIZE → PROVISION → SYNC → SETUP → EXEC.

Counterpart of the reference's sky/execution.py:31-642: the `Stage` enum,
the `_execute` wiring, `launch()` (all stages) and `exec_()` (SYNC_WORKDIR
+ EXEC only — the seconds-fast resubmit path, execution.py:553).
"""
from __future__ import annotations

import enum
from typing import Any, List, Optional, Set, Tuple, Union

from skypilot_tpu import admin_policy
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu import usage
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.backend import tpu_gang_backend
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)


class Stage(enum.Enum):
    """Reference execution.py:31 Stage enum (CLONE_DISK dropped: TPU VMs
    have no disk cloning)."""
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    PRE_EXEC = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _to_dag(entrypoint: Union[task_lib.Task, dag_lib.Dag]) -> dag_lib.Dag:
    if isinstance(entrypoint, task_lib.Task):
        with dag_lib.Dag() as d:
            d.add(entrypoint)
        return d
    return entrypoint


def _execute(
    entrypoint: Union[task_lib.Task, dag_lib.Dag],
    *,
    dryrun: bool = False,
    down: bool = False,
    stream_logs: bool = True,
    cluster_name: Optional[str] = None,
    detach_run: bool = False,
    stages: Optional[List[Stage]] = None,
    optimize_target: optimizer_lib.OptimizeTarget =
        optimizer_lib.OptimizeTarget.COST,
    idle_minutes_to_autostop: Optional[int] = None,
    retry_until_up: bool = False,
    quiet_optimizer: bool = False,
    blocked_resources: Optional[Set[Any]] = None,
    backend: Optional[backend_lib.Backend] = None,
) -> Tuple[Optional[int], Optional[backend_lib.ClusterHandle]]:
    """Run the requested lifecycle stages for a one-task DAG.

    Returns (job_id, handle) (reference _execute, execution.py:95).
    """
    dag = _to_dag(entrypoint)
    if len(dag.tasks) != 1:
        raise exceptions.NotSupportedError(
            'Only single-task DAGs can be executed directly; use managed '
            'jobs for pipelines (reference parity: execution.py:181).')
    dag = admin_policy.apply(dag)
    task = dag.tasks[0]
    task.validate()
    if cluster_name is None:
        cluster_name = common_utils.generate_cluster_name()
    common_utils.check_cluster_name_is_valid(cluster_name)
    usage.record_task(task)
    usage.record_cluster_name(cluster_name)
    stages = stages or list(Stage)

    handle: Optional[backend_lib.ClusterHandle] = None
    existing = global_user_state.get_cluster_from_name(cluster_name)
    if existing is not None and existing['status'] == \
            global_user_state.ClusterStatus.UP:
        handle = existing['handle']
    if existing is not None:
        # An existing cluster's substrate wins over the per-invocation
        # backend choice: `sky exec` (or a re-launch without --docker)
        # onto a docker cluster must not drive the gang backend against
        # a container handle, and vice versa.
        from skypilot_tpu import core
        chosen = core._backend(existing['handle'])  # pylint: disable=protected-access
        if backend is not None and backend.NAME != chosen.NAME:
            logger.warning(
                f'Cluster {cluster_name!r} runs on the {chosen.NAME} '
                f'backend; ignoring the requested {backend.NAME} '
                'backend for this invocation.')
        backend = chosen
    elif backend is None:
        backend = tpu_gang_backend.TpuGangBackend()

    if Stage.OPTIMIZE in stages and handle is None:
        optimizer_lib.optimize(dag, minimize=optimize_target,
                               blocked_resources=blocked_resources,
                               quiet=quiet_optimizer or dryrun)

    if Stage.PROVISION in stages:
        handle = backend.provision(task, task.best_resources, dryrun=dryrun,
                                   stream_logs=stream_logs,
                                   cluster_name=cluster_name,
                                   retry_until_up=retry_until_up)
    if handle is None:
        if dryrun:
            return None, None
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is not UP; cannot continue.')

    if Stage.SYNC_WORKDIR in stages and task.workdir is not None:
        backend.sync_workdir(handle, task.workdir)
    if Stage.SYNC_FILE_MOUNTS in stages and (task.file_mounts or
                                             task.storage_mounts):
        backend.sync_file_mounts(handle, task.file_mounts,
                                 task.storage_mounts)
    if Stage.SETUP in stages:
        backend.setup(handle, task)
    if Stage.PRE_EXEC in stages and idle_minutes_to_autostop is not None:
        backend.set_autostop(handle, idle_minutes_to_autostop, down=down)
    job_id: Optional[int] = None
    if Stage.EXEC in stages:
        job_id = backend.execute(handle, task, detach_run=detach_run,
                                 dryrun=dryrun)
    if Stage.DOWN in stages and down and \
            idle_minutes_to_autostop is None:
        if detach_run:
            # Job still running: autodown once the queue drains instead of
            # tearing down under it.
            backend.set_autostop(handle, 1, down=True)
            logger.info('--down with detached run: cluster will autodown '
                        '~1 minute after the job finishes.')
        else:
            # Non-detached execute streamed to completion.
            backend.teardown(handle, terminate=True)
            return job_id, None
    return job_id, handle


@usage.entrypoint('sky.launch')
def launch(
    task: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    down: bool = False,
    stream_logs: bool = True,
    detach_run: bool = False,
    optimize_target: optimizer_lib.OptimizeTarget =
        optimizer_lib.OptimizeTarget.COST,
    idle_minutes_to_autostop: Optional[int] = None,
    retry_until_up: bool = False,
    quiet_optimizer: bool = False,
    blocked_resources: Optional[Set[Any]] = None,
    backend: Optional[backend_lib.Backend] = None,
) -> Tuple[Optional[int], Optional[backend_lib.ClusterHandle]]:
    """Provision (or reuse) a cluster and run the task on it
    (reference execution.launch, execution.py:368)."""
    return _execute(
        task,
        backend=backend,
        dryrun=dryrun,
        down=down,
        stream_logs=stream_logs,
        cluster_name=cluster_name,
        detach_run=detach_run,
        optimize_target=optimize_target,
        idle_minutes_to_autostop=idle_minutes_to_autostop,
        retry_until_up=retry_until_up,
        quiet_optimizer=quiet_optimizer,
        blocked_resources=blocked_resources,
    )


@usage.entrypoint('sky.exec')
def exec_(  # pylint: disable=redefined-builtin
    task: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: str,
    *,
    dryrun: bool = False,
    detach_run: bool = False,
) -> Tuple[Optional[int], Optional[backend_lib.ClusterHandle]]:
    """Fast resubmit onto a live cluster: SYNC_WORKDIR + EXEC only
    (reference execution.exec, execution.py:553)."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist; `launch` first.')
    if record['status'] != global_user_state.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}, '
            'not UP.', cluster_status=record['status'],
            handle=record['handle'])
    return _execute(
        task,
        dryrun=dryrun,
        stream_logs=True,
        cluster_name=cluster_name,
        detach_run=detach_run,
        stages=[Stage.SYNC_WORKDIR, Stage.EXEC],
    )
