"""Client-side SQLite state: clusters, history, storage, enabled clouds.

Counterpart of the reference's sky/global_user_state.py:34-841.  Same
design: a single SQLite DB on the client holds the authoritative *intent*
records (cluster handles are pickled blobs), while cloud reality is
reconciled lazily by status refresh (backend_utils analog).  Usage
intervals are recorded per cluster for `cost-report`
(global_user_state.py:469-525).
"""
from __future__ import annotations

import enum
import json
import os
import pickle
import sqlite3
import threading
import time
import typing
from typing import Any, Dict, List, Optional, Set, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import paths

if typing.TYPE_CHECKING:
    from skypilot_tpu.backend import backend as backend_lib

logger = sky_logging.init_logger(__name__)


class ClusterStatus(enum.Enum):
    """Cluster lifecycle status (reference: sky/status_lib.ClusterStatus)."""
    INIT = 'INIT'          # provisioning in progress or unknown/interrupted
    UP = 'UP'              # all hosts running, runtime healthy
    STOPPED = 'STOPPED'    # instances stopped (impossible for TPU pods)

    def colored_str(self) -> str:
        color = {'INIT': '\x1b[94m', 'UP': '\x1b[92m',
                 'STOPPED': '\x1b[93m'}[self.value]
        return f'{color}{self.value}\x1b[0m'


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'


_CREATE_TABLES = """\
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT,
    autostop INTEGER DEFAULT -1,
    to_down INTEGER DEFAULT 0,
    owner TEXT DEFAULT NULL,
    metadata TEXT DEFAULT '{}',
    cluster_hash TEXT DEFAULT NULL,
    config_hash TEXT DEFAULT NULL,
    status_updated_at INTEGER DEFAULT NULL);
CREATE TABLE IF NOT EXISTS cluster_history (
    cluster_hash TEXT PRIMARY KEY,
    name TEXT,
    num_nodes INTEGER,
    requested_resources BLOB,
    launched_resources BLOB,
    usage_intervals BLOB);
CREATE TABLE IF NOT EXISTS storage (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT);
CREATE TABLE IF NOT EXISTS enabled_clouds (
    name TEXT PRIMARY KEY);
CREATE TABLE IF NOT EXISTS config (
    key TEXT PRIMARY KEY,
    value TEXT);
"""

_conn_local = threading.local()
_db_path_override: Optional[str] = None


def _db_path() -> str:
    return _db_path_override or paths.state_db_path()


def _conn() -> sqlite3.Connection:
    path = _db_path()
    cached = getattr(_conn_local, 'conn', None)
    cached_path = getattr(_conn_local, 'path', None)
    if cached is not None and cached_path == path:
        return cached
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite3.connect(path, timeout=10.0)
    conn.executescript(_CREATE_TABLES)
    _migrate(conn)
    conn.commit()
    _conn_local.conn = conn
    _conn_local.path = path
    return conn


# Columns added after the first released schema, with their ALTER
# defaults — a state.db written by an older client gains them on first
# open (reference analog: backward_compatibility_tests.sh guarantees an
# old client's state keeps working with new code).
_CLUSTER_COLUMN_MIGRATIONS = [
    ('owner', 'TEXT DEFAULT NULL'),
    ('metadata', "TEXT DEFAULT '{}'"),
    ('cluster_hash', 'TEXT DEFAULT NULL'),
    ('config_hash', 'TEXT DEFAULT NULL'),
    ('status_updated_at', 'INTEGER DEFAULT NULL'),
]


def _migrate(conn: sqlite3.Connection) -> None:
    cols = {r[1] for r in conn.execute('PRAGMA table_info(clusters)')}
    for name, decl in _CLUSTER_COLUMN_MIGRATIONS:
        if name not in cols:
            try:
                conn.execute(
                    f'ALTER TABLE clusters ADD COLUMN {name} {decl}')
            except sqlite3.OperationalError as e:
                # Another process migrated between our PRAGMA read and
                # the ALTER; anything else is a real failure.
                if 'duplicate column' not in str(e).lower():
                    raise


def reset_for_tests() -> None:
    """Drop cached connections so SKYTPU_STATE_DIR changes take effect."""
    if getattr(_conn_local, 'conn', None) is not None:
        _conn_local.conn.close()
        _conn_local.conn = None
        _conn_local.path = None


# ---------------------------------------------------------------------------
# Clusters
# ---------------------------------------------------------------------------
def add_or_update_cluster(cluster_name: str,
                          cluster_handle: 'backend_lib.ResourceHandle',
                          requested_resources: Optional[Set[Any]],
                          ready: bool,
                          config_hash: Optional[str] = None) -> None:
    """Record a cluster going INIT (launch started) or UP (ready)."""
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    now = int(time.time())
    handle_blob = pickle.dumps(cluster_handle)
    cluster_hash = _get_hash_for_existing_cluster(cluster_name) or \
        f'{cluster_name}-{now}'
    usage_intervals = _get_usage_intervals(cluster_hash)
    if ready:
        usage_intervals = _open_interval(usage_intervals, now)
    conn = _conn()
    with conn:
        conn.execute(
            'INSERT INTO clusters (name, launched_at, handle, last_use, '
            'status, cluster_hash, config_hash, status_updated_at) '
            'VALUES (?, ?, ?, ?, ?, ?, ?, ?) '
            'ON CONFLICT(name) DO UPDATE SET launched_at=excluded.launched_at,'
            ' handle=excluded.handle, last_use=excluded.last_use, '
            ' status=excluded.status, cluster_hash=excluded.cluster_hash, '
            ' config_hash=COALESCE(excluded.config_hash, config_hash), '
            ' status_updated_at=excluded.status_updated_at',
            (cluster_name, now, handle_blob, _last_use(), status.value,
             cluster_hash, config_hash, now))
        launched = pickle.dumps(
            getattr(cluster_handle, 'launched_resources', None))
        requested = pickle.dumps(requested_resources)
        num_nodes = getattr(cluster_handle, 'launched_nodes', None)
        conn.execute(
            'INSERT INTO cluster_history (cluster_hash, name, num_nodes, '
            'requested_resources, launched_resources, usage_intervals) '
            'VALUES (?, ?, ?, ?, ?, ?) '
            'ON CONFLICT(cluster_hash) DO UPDATE SET '
            ' num_nodes=excluded.num_nodes, '
            ' requested_resources=excluded.requested_resources, '
            ' launched_resources=excluded.launched_resources, '
            ' usage_intervals=excluded.usage_intervals',
            (cluster_hash, cluster_name, num_nodes, requested, launched,
             pickle.dumps(usage_intervals)))


def _last_use() -> str:
    import sys
    return ' '.join(sys.argv)


def _open_interval(intervals: List[Tuple[int, Optional[int]]],
                   now: int) -> List[Tuple[int, Optional[int]]]:
    if intervals and intervals[-1][1] is None:
        return intervals
    return intervals + [(now, None)]


def _close_interval(intervals: List[Tuple[int, Optional[int]]],
                    now: int) -> List[Tuple[int, Optional[int]]]:
    if intervals and intervals[-1][1] is None:
        start, _ = intervals[-1]
        return intervals[:-1] + [(start, now)]
    return intervals


def update_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    now = int(time.time())
    conn = _conn()
    with conn:
        conn.execute(
            'UPDATE clusters SET status=?, status_updated_at=? WHERE name=?',
            (status.value, now, cluster_name))
    if status != ClusterStatus.UP:
        cluster_hash = _get_hash_for_existing_cluster(cluster_name)
        if cluster_hash is not None:
            intervals = _close_interval(_get_usage_intervals(cluster_hash),
                                        now)
            with conn:
                conn.execute(
                    'UPDATE cluster_history SET usage_intervals=? '
                    'WHERE cluster_hash=?',
                    (pickle.dumps(intervals), cluster_hash))


def update_cluster_handle(cluster_name: str,
                          cluster_handle: Any) -> None:
    conn = _conn()
    with conn:
        conn.execute('UPDATE clusters SET handle=? WHERE name=?',
                     (pickle.dumps(cluster_handle), cluster_name))


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    """On stop: keep record as STOPPED (handle IPs stale-cleared by the
    backend); on terminate: delete the row but close the usage interval
    first so cost-report still sees it."""
    now = int(time.time())
    cluster_hash = _get_hash_for_existing_cluster(cluster_name)
    conn = _conn()
    if cluster_hash is not None:
        intervals = _close_interval(_get_usage_intervals(cluster_hash), now)
        with conn:
            conn.execute(
                'UPDATE cluster_history SET usage_intervals=? '
                'WHERE cluster_hash=?',
                (pickle.dumps(intervals), cluster_hash))
    with conn:
        if terminate:
            conn.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
        else:
            conn.execute(
                'UPDATE clusters SET status=?, status_updated_at=? '
                'WHERE name=?',
                (ClusterStatus.STOPPED.value, now, cluster_name))


def get_cluster_from_name(
        cluster_name: str) -> Optional[Dict[str, Any]]:
    rows = _conn().execute('SELECT * FROM clusters WHERE name=?',
                           (cluster_name,)).fetchall()
    if not rows:
        return None
    return _row_to_record(rows[0])


def _row_to_record(row: Tuple) -> Dict[str, Any]:
    (name, launched_at, handle, last_use, status, autostop, to_down, owner,
     metadata, cluster_hash, config_hash, status_updated_at) = row
    return {
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle),
        'last_use': last_use,
        'status': ClusterStatus(status),
        'autostop': autostop,
        'to_down': bool(to_down),
        'owner': json.loads(owner) if owner else None,
        'metadata': json.loads(metadata),
        'cluster_hash': cluster_hash,
        'config_hash': config_hash,
        'status_updated_at': status_updated_at,
    }


def get_clusters() -> List[Dict[str, Any]]:
    rows = _conn().execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_row_to_record(r) for r in rows]


def get_handle_from_cluster_name(cluster_name: str) -> Optional[Any]:
    record = get_cluster_from_name(cluster_name)
    return None if record is None else record['handle']

def set_cluster_autostop_value(cluster_name: str, idle_minutes: int,
                               to_down: bool) -> None:
    conn = _conn()
    with conn:
        conn.execute('UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
                     (idle_minutes, int(to_down), cluster_name))


def get_cluster_metadata(cluster_name: str) -> Optional[Dict[str, Any]]:
    record = get_cluster_from_name(cluster_name)
    return None if record is None else record['metadata']


def set_cluster_metadata(cluster_name: str, metadata: Dict[str,
                                                           Any]) -> None:
    conn = _conn()
    with conn:
        conn.execute('UPDATE clusters SET metadata=? WHERE name=?',
                     (json.dumps(metadata), cluster_name))


def set_owner_identity_for_cluster(cluster_name: str,
                                   owner_identity: Optional[List[str]]
                                   ) -> None:
    if owner_identity is None:
        return
    conn = _conn()
    with conn:
        conn.execute('UPDATE clusters SET owner=? WHERE name=?',
                     (json.dumps(owner_identity), cluster_name))


def _get_hash_for_existing_cluster(cluster_name: str) -> Optional[str]:
    rows = _conn().execute('SELECT cluster_hash FROM clusters WHERE name=?',
                           (cluster_name,)).fetchall()
    return rows[0][0] if rows else None


def _get_usage_intervals(
        cluster_hash: Optional[str]
) -> List[Tuple[int, Optional[int]]]:
    if cluster_hash is None:
        return []
    rows = _conn().execute(
        'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
        (cluster_hash,)).fetchall()
    if not rows or rows[0][0] is None:
        return []
    return pickle.loads(rows[0][0])


def get_cluster_history() -> List[Dict[str, Any]]:
    """All clusters ever launched, with usage intervals (cost-report)."""
    rows = _conn().execute(
        'SELECT cluster_hash, name, num_nodes, requested_resources, '
        'launched_resources, usage_intervals FROM cluster_history').fetchall()
    out = []
    current = {r['name'] for r in get_clusters()}
    for (cluster_hash, name, num_nodes, requested, launched,
         intervals) in rows:
        out.append({
            'cluster_hash': cluster_hash,
            'name': name,
            'num_nodes': num_nodes,
            'requested_resources':
                pickle.loads(requested) if requested else None,
            'launched_resources':
                pickle.loads(launched) if launched else None,
            'usage_intervals':
                pickle.loads(intervals) if intervals else [],
            'still_exists': name in current,
        })
    return out


# ---------------------------------------------------------------------------
# Enabled clouds (sky check analog)
# ---------------------------------------------------------------------------
def get_cached_enabled_clouds() -> List[str]:
    rows = _conn().execute('SELECT name FROM enabled_clouds').fetchall()
    return [r[0] for r in rows]


def set_enabled_clouds(enabled_clouds: List[str]) -> None:
    conn = _conn()
    with conn:
        conn.execute('DELETE FROM enabled_clouds')
        conn.executemany('INSERT INTO enabled_clouds (name) VALUES (?)',
                         [(c,) for c in enabled_clouds])


# ---------------------------------------------------------------------------
# Storage
# ---------------------------------------------------------------------------
def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: StorageStatus) -> None:
    conn = _conn()
    with conn:
        conn.execute(
            'INSERT INTO storage (name, launched_at, handle, last_use, '
            'status) VALUES (?, ?, ?, ?, ?) '
            'ON CONFLICT(name) DO UPDATE SET handle=excluded.handle, '
            'status=excluded.status, last_use=excluded.last_use',
            (storage_name, int(time.time()), pickle.dumps(storage_handle),
             _last_use(), storage_status.value))


def remove_storage(storage_name: str) -> None:
    conn = _conn()
    with conn:
        conn.execute('DELETE FROM storage WHERE name=?', (storage_name,))


def get_storage() -> List[Dict[str, Any]]:
    rows = _conn().execute('SELECT * FROM storage').fetchall()
    return [{
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle),
        'last_use': last_use,
        'status': StorageStatus(status),
    } for name, launched_at, handle, last_use, status in rows]


def get_handle_from_storage_name(storage_name: str) -> Optional[Any]:
    for record in get_storage():
        if record['name'] == storage_name:
            return record['handle']
    return None
