"""The fleet wire contract: routes, headers, statuses, env vars, SKHO.

PRs 15–17 grew a real cross-process surface — replica HTTP servers
(`infer/server.py`), the self-healing router (`serve/router.py`), the
dashboard (`serve/dashboard.py`), and the clients that call them
(router proxy/scrapes, handoff relays, peer prefix fetches, the
benches).  Each side of that surface is easy to change alone and
silently wrong to change alone: a renamed header, a new status code no
client classifies, or an env var with two different inline defaults
only surfaces in an e2e run, or in production.

This module is the single source of truth for that surface, in the
same pattern as ``observability.METRIC_CONTRACT`` /
``observability.events.EVENT_CONTRACT``:

- ``ROUTE_CONTRACT`` — every (method, path) the fleet serves, which
  server(s) own it, the statuses it may emit (and how clients must
  handle each), and the custom headers on either side of it.
- ``HEADER_CONTRACT`` — every ``X-Skytpu-*`` / ``X-Request-Id``
  header: who stamps it, who reads it.
- ``ENV_CONTRACT`` — every ``SKYTPU_*`` environment variable: its
  default, its parser, and the one-line doc that generates the
  "Environment variables" table in docs/architecture.md.
- the SKHO artifact version constants (``infer/handoff.py`` imports
  them from here, so the wire-format version and the header names
  have exactly one home).

`devtools/rules/{route,header,status,env}_discipline.py` mechanize the
contract: an AST extraction pass (`devtools/protocol_analysis.py`)
recovers both sides of the wire from the skylint whole-program index
and checks them against these tables, so a protocol drift is a lint
finding with a cross-file call chain instead of a production incident.

Stdlib only, imports nothing from the package: the router, the
replica server, `infer/handoff.py`, and skylint itself must all be
able to load it without touching a device runtime.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple

# ---------------------------------------------------------------------
# SKHO artifact versioning (single source; infer/handoff.py re-exports)
# ---------------------------------------------------------------------

# 'SKHO' = SKytpu HandOff.  Bump SKHO_VERSION on ANY layout or
# semantics change — receivers reject other versions (HTTP 409,
# fail-closed) instead of guessing.
SKHO_MAGIC = b'SKHO'
SKHO_VERSION = 2

# Version matrix (docs/architecture.md renders this verbatim): what
# each wire version can carry.  A v1 reader rejects v2 artifacts and
# vice versa — there is no negotiation, by design.
SKHO_VERSION_MATRIX: Mapping[int, str] = {
    1: 'prefill handoff artifacts only; uncompressed tensor section',
    2: "artifact kinds ('prefill', 'slot' migration, 'kv_prefix' "
       'fleet transfer) + optional zlib tensor compression',
}

# ---------------------------------------------------------------------
# Headers
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HeaderSpec:
    """One custom wire header: which side stamps it, which reads it.

    ``stamped_by``/``read_by`` are informational role names
    ('client', 'router', 'replica', ...); the header-discipline rule
    derives the *actual* stamp/read sites from the AST and flags
    one-sided headers — an empty ``read_by`` documents a deliberately
    one-sided (diagnostic) header."""
    name: str
    stamped_by: Tuple[str, ...]
    read_by: Tuple[str, ...]
    doc: str


# Canonical spellings.  Anything matching X-Skytpu-* or X-Request-Id
# that is NOT one of these names (case-insensitive) is a
# header-discipline finding — the typo'd side would wait forever for a
# header nobody sends.
REQUEST_ID_HEADER = 'X-Request-Id'
TRACE_HEADER = 'X-Skytpu-Trace'
DECODE_TARGET_HEADER = 'X-Skytpu-Decode-Target'
PREFIX_PEER_HEADER = 'X-Skytpu-Prefix-Peer'
DEADLINE_HEADER = 'X-Skytpu-Deadline-S'
SERVED_BY_HEADER = 'X-Served-By'

HEADER_CONTRACT: Dict[str, HeaderSpec] = {
    spec.name: spec for spec in (
        HeaderSpec(
            REQUEST_ID_HEADER,
            stamped_by=('client', 'router', 'replica'),
            read_by=('router', 'replica', 'client'),
            doc='External request id; echoed on every response and '
                'used as the distributed trace id (the /traces stitch '
                'key).  Routers generate one when the client sends '
                'none or a non `[A-Za-z0-9._:-]{1,64}` token.'),
        HeaderSpec(
            TRACE_HEADER,
            stamped_by=('router',),
            read_by=('replica',),
            doc='`<trace_id>/<parent_span_id>` propagation from the '
                "router's per-attempt span to the replica, so replica "
                'engine traces nest under the exact attempt that '
                'reached them.'),
        HeaderSpec(
            DECODE_TARGET_HEADER,
            stamped_by=('router',),
            read_by=('replica',),
            doc='Router -> prefill-replica: the decode replica the '
                'rendezvous hash picked; the prefill replica POSTs '
                'the SKHO artifact to its /handoff.'),
        HeaderSpec(
            PREFIX_PEER_HEADER,
            stamped_by=('router',),
            read_by=('replica',),
            doc='Router -> replica: the rendezvous OWNER of this '
                "request's prefix-affinity key; a saturation-fallback "
                "replica asks the owner's GET /kv_prefix for spilled "
                'prefix pages before prefilling from zero.'),
        HeaderSpec(
            DEADLINE_HEADER,
            stamped_by=('replica',),
            read_by=('replica',),
            doc='Prefill -> decode replica on POST /handoff: the '
                "relayed request's remaining deadline budget in "
                'seconds, so the decode side sheds work the original '
                "client already gave up on instead of inheriting the "
                'default deadline.'),
        HeaderSpec(
            SERVED_BY_HEADER,
            stamped_by=('router',),
            read_by=(),     # deliberately one-sided: a human/debug aid
            doc='Router -> client diagnostic: the replica URL that '
                'actually served a proxied response (failovers make '
                '"which replica was that?" otherwise unanswerable).'),
    )
}

# ---------------------------------------------------------------------
# Routes
# ---------------------------------------------------------------------

# How a client must handle a server-emitted status:
#   'branch'  — some client must branch on the literal code (or a
#               named retry-classifier tuple containing it); a status
#               nobody classifies is a latent outage mode.
#   'generic' — a generic HTTPError/error arm suffices (diagnostic or
#               low-stakes codes).
BRANCH = 'branch'
GENERIC = 'generic'


@dataclasses.dataclass(frozen=True)
class RouteSpec:
    """One (method, path) of the fleet wire surface."""
    method: str
    path: str
    servers: Tuple[str, ...]          # 'replica' | 'router' | 'dashboard'
    statuses: Mapping[int, str]       # code -> BRANCH | GENERIC
    # Statuses that are fail-closed: a client must treat them as
    # terminal for this artifact/request — retrying them (on the same
    # or another peer) can never succeed and may duplicate output.
    fail_closed: Tuple[int, ...] = ()
    request_headers: Tuple[str, ...] = ()
    response_headers: Tuple[str, ...] = ()
    doc: str = ''


def _route(method, path, servers, statuses, **kw) -> RouteSpec:
    return RouteSpec(method=method, path=path, servers=servers,
                     statuses=statuses, **kw)


# Terminal statuses on POST /handoff: the two ends disagree about the
# artifact (wire version, format) — retrying on another peer can never
# succeed and may duplicate output.  A plain literal tuple so client
# code (and skylint's constant resolver) can share it by name.
HANDOFF_FAIL_CLOSED = (400, 409)

# The replica server's generic arms apply to every route its dispatch
# serves: 404 unknown path, 405+Allow wrong method, 500 handler error.
_REPLICA_GENERIC = {404: GENERIC, 405: GENERIC, 500: GENERIC}
# Every replica POST route shares one dispatch try/except, so every
# one of its arms (shed 503, deadline 504, handoff 400/409, bad
# payload 400, crash 500) is a possible answer on every POST route.
# Which of them a client must BRANCH on is per-route below.
_REPLICA_POST = {200: GENERIC, 400: GENERIC, 404: GENERIC,
                 405: GENERIC, 409: GENERIC, 500: GENERIC,
                 503: GENERIC, 504: GENERIC}

ROUTE_CONTRACT: Dict[Tuple[str, str], RouteSpec] = {
    (spec.method, spec.path): spec for spec in (
        # -- replica + router shared surface --------------------------
        _route('GET', '/health', ('replica', 'router'),
               {200: GENERIC, 503: BRANCH, **_REPLICA_GENERIC},
               response_headers=(REQUEST_ID_HEADER,),
               doc='Three-state health: ok / draining / unhealthy.  '
                   '503 carries the unroutable states — probes must '
                   'branch on it (a draining listener still accepts '
                   'TCP).'),
        _route('GET', '/metrics', ('replica', 'router'),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Prometheus exposition (per-process registry).'),
        _route('GET', '/events', ('replica', 'router'),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Flight-recorder ring snapshot (?limit=).'),
        _route('GET', '/traces', ('replica', 'router'),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Request traces; on the router ?id=&stitch=1 joins '
                   'router spans with replica engine timelines.'),
        _route('GET', '/v1/models', ('replica', 'router'),
               {200: GENERIC, 502: GENERIC, 503: GENERIC,
                **_REPLICA_GENERIC},
               doc='OpenAI-compatible model listing (the router '
                   'proxies it to a replica, so the 502/503 '
                   'no-routable-replica arms apply).'),
        # -- replica-only ---------------------------------------------
        _route('GET', '/kv_prefix', ('replica',),
               {200: GENERIC, 400: GENERIC, 404: GENERIC,
                **_REPLICA_GENERIC},
               doc='Fleet prefix-cache tier: the leading run of '
                   'host-spilled KV pages for ?hashes=, as an SKHO '
                   'kv_prefix artifact.  Misses (404) and skew are '
                   'survivable by design — the caller just '
                   'prefills.'),
        _route('GET', '/profile/steps', ('replica',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Step-ledger snapshot (?limit=).'),
        _route('GET', '/profile/timeline', ('replica',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Perfetto-style timeline document (?traces=).'),
        _route('POST', '/generate', ('replica', 'router'),
               {**_REPLICA_POST, 500: BRANCH, 502: BRANCH, 503: BRANCH},
               request_headers=(REQUEST_ID_HEADER, TRACE_HEADER,
                                DECODE_TARGET_HEADER,
                                PREFIX_PEER_HEADER),
               response_headers=(REQUEST_ID_HEADER, SERVED_BY_HEADER),
               doc='Native generation (blocking or ndjson stream).  '
                   '503+Retry-After = shed (retry at the given pace); '
                   '504 = deadline exceeded (deterministic, relay '
                   'as-is); 500/502 through the router are retried on '
                   'another replica by the failover classifier.'),
        _route('POST', '/v1/completions', ('replica', 'router'),
               {**_REPLICA_POST, 500: BRANCH, 502: BRANCH, 503: BRANCH},
               request_headers=(REQUEST_ID_HEADER, TRACE_HEADER,
                                DECODE_TARGET_HEADER,
                                PREFIX_PEER_HEADER),
               response_headers=(REQUEST_ID_HEADER, SERVED_BY_HEADER),
               doc='OpenAI completions (+SSE streaming).'),
        _route('POST', '/v1/chat/completions', ('replica', 'router'),
               {**_REPLICA_POST, 500: BRANCH, 502: BRANCH, 503: BRANCH},
               request_headers=(REQUEST_ID_HEADER, TRACE_HEADER,
                                DECODE_TARGET_HEADER,
                                PREFIX_PEER_HEADER),
               response_headers=(REQUEST_ID_HEADER, SERVED_BY_HEADER),
               doc='OpenAI chat completions (+SSE streaming).'),
        _route('POST', '/drain', ('replica',),
               dict(_REPLICA_POST),
               doc='Supervisor -> replica: stop admitting, finish or '
                   'migrate in-flight work ({"migrate": bool, '
                   '"targets": [...]}).  Best-effort: callers fall '
                   'back to the drain deadline on any failure.'),
        _route('POST', '/handoff', ('replica',),
               {**_REPLICA_POST, 400: BRANCH, 409: BRANCH,
                503: BRANCH},
               fail_closed=HANDOFF_FAIL_CLOSED,
               request_headers=(REQUEST_ID_HEADER, DEADLINE_HEADER),
               doc='SKHO artifact ingest (disaggregated decode, live '
                   'migration).  409 = version/geometry skew '
                   '(HandoffVersionError): FAIL-CLOSED — every peer '
                   'runs the same build mid-rollout, so retrying on '
                   'another peer cannot succeed and must not be '
                   'attempted.  400 = malformed artifact, equally '
                   'terminal.  503 = shed; the artifact is immutable '
                   'bytes, so trying the NEXT peer is safe.'),
        _route('POST', '/profile/device', ('replica',),
               dict(_REPLICA_POST),
               doc='On-demand device profiler ({"steps": n}); 409 '
                   'while a capture is already active '
                   '(ProfileActiveError: single-flight, wait it '
                   'out rather than retrying).'),
        # -- router-only ----------------------------------------------
        _route('GET', '/fleet/metrics', ('router',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Federated exposition: every routable replica\'s '
                   '/metrics merged, each series labeled '
                   'replica="url".'),
        _route('GET', '/fleet/slo', ('router',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Fleet SLO roll-up: goodput vs target, burn '
                   'rate.'),
        _route('GET', '/fleet/profile', ('router',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Fleet step-ledger roll-up (?limit= per replica).'),
        _route('GET', '/router/replicas', ('router',),
               {200: GENERIC, **_REPLICA_GENERIC},
               doc='Per-replica routing views: health, breaker, '
                   'inflight, queue depth, role.'),
        # -- controller (serve/controller.py) -------------------------
        _route('POST', '/controller/load_balancer_sync',
               ('controller',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC,
                500: GENERIC},
               doc='Load balancer -> controller heartbeat: request '
                   'counts up, fresh replica URL set back.  '
                   'Best-effort; the balancer keeps serving its last '
                   'known set on any failure.'),
        _route('POST', '/controller/update_service',
               ('controller',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC,
                500: GENERIC},
               doc='Blue-green rollout trigger: adopt the already '
                   'persisted spec for the given version.'),
        _route('GET', '/controller/health', ('controller',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='Controller liveness probe; echoes the service '
                   'name.'),
        _route('GET', '/services', ('controller',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='Browsable `sky serve status` analog (HTML), '
                   'scoped to this controller\'s service.'),
        # -- dashboard ------------------------------------------------
        _route('GET', '/', ('dashboard',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='HTML services+fleet page.'),
        _route('GET', '/healthz', ('dashboard',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='Dashboard liveness probe.'),
        _route('GET', '/api/services', ('dashboard', 'controller'),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='JSON service/replica snapshot (the controller '
                   'serves the same shape so the dashboard page '
                   'works against either).'),
        _route('GET', '/api/fleet', ('dashboard',),
               {200: GENERIC, 404: GENERIC, 405: GENERIC},
               doc='Fleet snapshot proxied from the router; 404 until '
                   'started with --router (the page script branches '
                   'on it to hide the fleet section).'),
    )
}


def routes_for(server: str) -> Dict[str, Tuple[str, ...]]:
    """{'GET': (paths...), 'POST': (paths...)} for one server role —
    what the round-trip tests compare against the live dispatch
    tables."""
    out: Dict[str, list] = {}
    for (method, path), spec in sorted(ROUTE_CONTRACT.items()):
        if server in spec.servers:
            out.setdefault(method, []).append(path)
    return {m: tuple(ps) for m, ps in out.items()}


# ---------------------------------------------------------------------
# Environment variables
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """One SKYTPU_* environment variable.

    ``default`` is the exact literal a read site must pass as its
    inline default (env-discipline flags divergence); None when the
    default is computed (cwd, a path expansion, a batch-size
    multiple) or when unset simply disables the feature —
    ``default_doc`` is what the docs table shows either way."""
    name: str
    default: Optional[str]
    parser: str                       # int|float|str|path|flag|schedule
    default_doc: str
    doc: str


def _env(name, default, parser, doc,
         default_doc: Optional[str] = None) -> EnvSpec:
    return EnvSpec(name=name, default=default, parser=parser,
                   default_doc=(default_doc if default_doc is not None
                                else (default if default not in (None, '')
                                      else 'unset')),
                   doc=doc)


ENV_CONTRACT: Dict[str, EnvSpec] = {
    spec.name: spec for spec in (
        # -- serving: replica server admission/lifecycle --------------
        _env('SKYTPU_REQUEST_DEADLINE_S', '600', 'float',
             'Default per-request deadline when the payload carries '
             'no deadline_s; admission sheds work that cannot meet '
             'it.'),
        _env('SKYTPU_MAX_QUEUE_DEPTH', None, 'int',
             'Admission queue-depth bound; deeper queues shed with '
             '503+Retry-After.', default_doc='8 * max_batch_size'),
        _env('SKYTPU_STREAM_TOKEN_TIMEOUT_S', '120', 'float',
             'Inter-token timeout for streamed responses and handoff '
             'relays; a stalled decode cancels instead of hanging '
             'the client.'),
        _env('SKYTPU_STEP_STALL_TIMEOUT_S', '120', 'float',
             'Watchdog: a decode step exceeding this marks the '
             'replica unhealthy.'),
        _env('SKYTPU_LOOP_MAX_RESTARTS', '5', 'int',
             'Supervised decode-loop restarts tolerated within the '
             'restart window before the replica goes unhealthy.'),
        _env('SKYTPU_LOOP_RESTART_WINDOW_S', '60', 'float',
             'Sliding window for the restart budget.'),
        _env('SKYTPU_DRAIN_TIMEOUT_S', '600', 'float',
             'POST /drain grace: in-flight work gets this long '
             'before hard shutdown.'),
        _env('SKYTPU_SHUTDOWN_JOIN_S', '5', 'float',
             'Thread-join grace during server shutdown.'),
        _env('SKYTPU_PREEMPT_NOTICE_S', '0', 'float',
             'Simulated preemption notice for the replica supervisor '
             '(tests/chaos; 0 = disabled).'),
        # -- serving: SLO + router ------------------------------------
        _env('SKYTPU_SLO_TTFT_S', None, 'float',
             'TTFT SLO target in seconds for goodput accounting; '
             'unset or <= 0 disables that SLO.',
             default_doc='unset (disabled)'),
        _env('SKYTPU_SLO_TPOT_S', None, 'float',
             'TPOT SLO target in seconds; unset or <= 0 disables.',
             default_doc='unset (disabled)'),
        _env('SKYTPU_SLO_GOODPUT_TARGET', '', 'float',
             'Fleet goodput target in (0, 1) for /fleet/slo burn '
             'rate.', default_doc='0.99'),
        # -- serving: handoff/migration/cache -------------------------
        _env('SKYTPU_HANDOFF_COMPRESS', None, 'flag',
             'Non-empty enables the SKHO v2 zlib tensor section on '
             'outbound handoff artifacts.',
             default_doc='unset (uncompressed)'),
        # -- observability --------------------------------------------
        _env('SKYTPU_TRACE_RING', '', 'int',
             'Completed-trace ring capacity for the engine '
             'TraceStore.', default_doc='256'),
        _env('SKYTPU_TRACE_JSONL', None, 'path',
             'Mirror every trace transition to this JSONL file.',
             default_doc='unset (off)'),
        _env('SKYTPU_STEP_LEDGER', '1', 'flag',
             "'0' disables the per-step performance ledger."),
        _env('SKYTPU_STEP_LEDGER_CAP', '', 'int',
             'Step-ledger ring capacity.', default_doc='512'),
        _env('SKYTPU_PROFILE_DIR', '', 'path',
             'Directory for on-demand device-profiler captures.',
             default_doc='SKYTPU_LOG_DIR'),
        _env('SKYTPU_LOG_DIR', None, 'path',
             'Root for log/profile artifacts.',
             default_doc='os.getcwd()'),
        _env('SKYTPU_LOG_JSON', None, 'flag',
             'Non-empty switches logging to one-JSON-object-per-line '
             '(machine ingestion).', default_doc='unset (text)'),
        _env('SKYTPU_DEBUG', None, 'flag',
             'Non-empty enables debug-level logging and timeline '
             'annotations.', default_doc='unset'),
        _env('SKYTPU_TIMELINE_FILE', None, 'path',
             'Host-side timeline event sink.',
             default_doc='~/.skytpu/timeline-<pid>.jsonl'),
        # -- chaos ----------------------------------------------------
        _env('SKYTPU_CHAOS', '', 'schedule',
             "Fault-injection schedule ('point:p=..,seed=..;...'); "
             'unset disables every fault point.',
             default_doc='unset (no faults)'),
        # -- workload stack (train/ops/parallel) ----------------------
        _env('SKYTPU_PREFETCH_DEPTH', '2', 'int',
             'Device prefetch depth of the input pipeline.'),
        _env('SKYTPU_PROFILE', None, 'flag',
             'Non-empty captures a jax.profiler trace around the '
             'trainer steady state.', default_doc='unset'),
        _env('SKYTPU_FORCE_PALLAS', '', 'flag',
             'Force the Pallas kernel paths even where the reference '
             'path would be picked.', default_doc='unset'),
        _env('SKYTPU_BACKEND_INIT_RETRIES', '3', 'int',
             'Attempts to initialize the jax backend before giving '
             'up.'),
        _env('SKYTPU_BACKEND_INIT_BACKOFF_S', '5', 'float',
             'Base backoff between backend-init attempts.'),
        _env('SKYTPU_BACKEND_INIT_TIMEOUT_S', '180', 'float',
             'Per-attempt backend-init watchdog.'),
        # -- orchestrator ---------------------------------------------
        _env('SKYTPU_STATE_DIR', None, 'path',
             'Root of the local state database and logs.',
             default_doc='~/.skytpu'),
        _env('SKYTPU_USER_HASH', None, 'str',
             'Stable user hash override for cluster-name '
             'namespacing.', default_doc='derived'),
        _env('SKYTPU_LOCAL_HOST_ROOT', None, 'path',
             'Local-process cloud: fake host root for agent '
             'daemon/RPC tests.', default_doc='unset'),
        _env('SKYTPU_QUEUED_TIMEOUT', '1800', 'float',
             'GCP TPU QUEUED->PROVISIONING wait before failing over '
             'to the next zone.'),
        _env('SKYTPU_AWS_SG_DELETE_WAIT_S', '120', 'float',
             'AWS security-group delete wait during teardown.'),
        _env('SKYTPU_JOBS_DASHBOARD_HOST', '127.0.0.1', 'str',
             'Bind host of the managed-jobs dashboard.'),
        _env('SKYTPU_JOBS_DASHBOARD_PORT', None, 'int',
             'Port of the managed-jobs dashboard.',
             default_doc='5050'),
        # -- bench ----------------------------------------------------
        _env('SKYTPU_BENCH_TOTAL_BUDGET_S', '1500', 'float',
             'Total wall budget the bench ladder divides across its '
             'rungs.'),
        _env('SKYTPU_BENCH_E2E_DEADLINE_S', '3600', 'float',
             'Hard deadline for one e2e bench attempt.'),
        _env('SKYTPU_BENCH_DIRECT_TIMEOUT_S', '2400', 'float',
             'Watchdog for one --direct bench run.'),
        _env('SKYTPU_BENCH_DIRECT_ATTEMPTS', '3', 'int',
             'Direct-rung attempts before falling back to the '
             'cache.'),
        _env('SKYTPU_BENCH_DIRECT_SPACING_S', '600', 'float',
             'Spacing between direct-rung attempts.'),
        _env('SKYTPU_BENCH_REGRESSION_TOL', '0.25', 'float',
             'Relative tolerance of the --check-baseline regression '
             'gate.'),
        _env('SKYTPU_BENCH_CACHE', None, 'path',
             'Location of the last-good bench capture.',
             default_doc='<repo>/BENCH_cache.json'),
        _env('SKYTPU_BENCH_CACHE_MAX_AGE_S', None, 'float',
             'Max age before a cached capture stops counting as a '
             'result.', default_doc='86400'),
        _env('SKYTPU_BENCH_PROBE_LOG', None, 'path',
             'Probe-ladder JSONL log location.',
             default_doc='<repo>/BENCH_probes.jsonl'),
        _env('SKYTPU_BENCH_TINY', None, 'flag',
             "'1' shrinks bench shapes to CPU-smoke scale.",
             default_doc='unset'),
    )
}


def env_table_rows() -> Tuple[Tuple[str, str, str, str], ...]:
    """(name, default, parser, doc) rows, sorted — the docs generator
    and its checker both consume this, so the table cannot drift."""
    return tuple((s.name, s.default_doc, s.parser, s.doc)
                 for _, s in sorted(ENV_CONTRACT.items()))
