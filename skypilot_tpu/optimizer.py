"""Cost/time optimizer over the cloud catalog.

Counterpart of the reference's sky/optimizer.py:110-1345:
  - `_fill_in_launchable_resources` concretizes each task's partial
    Resources into per-cloud launchable candidates (optimizer.py:1257),
    honoring the enabled-cloud set and a *blocklist* that the failover
    engine grows as zones/regions/clouds fail (cloud_vm_ray_backend.py:
    2093-2150 re-optimize-with-blocklist loop).
  - chain DAGs are solved by DP over topological order with egress cost
    between consecutive tasks (optimizer.py:411); general DAGs by
    brute-force enumeration for small graphs (the reference uses an ILP via
    pulp, optimizer.py:472 — pulp is unavailable here, and real DAGs are
    small chains, so exhaustive search with a node bound is equivalent).
  - prints a candidate table (optimizer.py:720).

TPU specifics: time estimation uses the generation's aggregate bf16 FLOPs
so that e.g. v5p vs v5e tradeoffs are priced as tokens/sec/$ rather than
instance-hours only.
"""
from __future__ import annotations

import collections
import enum
import typing
from typing import Any, Dict, List, Optional, Set, Tuple

# Import the module through sys.modules (the package attribute `check` is
# the function exported by the SDK).
from skypilot_tpu.check import get_cached_enabled_clouds_or_refresh
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

_DEFAULT_TIME_ESTIMATE_HOURS = 1.0


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


def _estimate_runtime_hours(task: 'task_lib.Task',
                            resources: resources_lib.Resources) -> float:
    """Relative runtime estimate.  Without user-provided estimates the
    reference assumes 1 hour for every candidate (optimizer.py:241); we
    additionally scale TPU candidates inversely with aggregate bf16 FLOPs
    so TIME optimization meaningfully ranks slice shapes."""
    del task
    base = _DEFAULT_TIME_ESTIMATE_HOURS
    spec = resources.tpu_slice
    if spec is not None:
        # Normalize to a v5e-8 slice as 1.0 "work unit".
        reference_tflops = 8 * 197.0
        return base * reference_tflops / max(spec.total_bf16_tflops, 1.0)
    return base


def _resources_blocked(resources: resources_lib.Resources,
                       blocked: Optional[Set[resources_lib.Resources]]
                       ) -> bool:
    """A blocklist entry with unset fields acts as a wildcard: blocking
    (cloud=GCP, region=us-central2) blocks every zone/type in that region
    (reference: Resources.should_be_blocked_by, used by the failover loop)."""
    if not blocked:
        return False
    for b in blocked:
        if b.cloud is not None and not b.cloud.is_same_cloud(resources.cloud):
            continue
        if b.region is not None and b.region != resources.region:
            continue
        if b.zone is not None and b.zone != resources.zone:
            continue
        if (b.instance_type is not None and
                b.instance_type != resources.instance_type):
            continue
        if b.accelerators is not None and \
                b.accelerators != resources.accelerators:
            continue
        if b.use_spot_specified and b.use_spot != resources.use_spot:
            continue
        return True
    return False


def _fill_in_launchable_resources(
    task: 'task_lib.Task',
    blocked_resources: Optional[Set[resources_lib.Resources]],
    quiet: bool = False,
) -> Tuple[Dict[resources_lib.Resources, List[resources_lib.Resources]],
           List[str]]:
    """For each of the task's candidate Resources, list feasible launchable
    concretizations across enabled clouds (reference optimizer.py:1257)."""
    enabled_clouds = get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access=True)
    launchable: Dict[resources_lib.Resources,
                     List[resources_lib.Resources]] = {}
    all_fuzzy: List[str] = []
    hints: List[str] = []
    for resources in task.get_preferred_resources():
        candidates: List[resources_lib.Resources] = []
        if resources.cloud is not None:
            clouds_to_try = [resources.cloud]
            if not any(c.is_same_cloud(resources.cloud)
                       for c in enabled_clouds):
                hints.append(
                    f'{resources.cloud} is not enabled; run `skytpu check`.')
                clouds_to_try = []
        else:
            clouds_to_try = enabled_clouds
        for cloud in clouds_to_try:
            try:
                feasible = cloud.get_feasible_launchable_resources(
                    resources, task.num_nodes)
            except exceptions.ResourcesValidationError as e:
                hints.append(str(e))
                continue
            all_fuzzy.extend(feasible.fuzzy_candidate_list)
            if feasible.hint:
                hints.append(feasible.hint)
            for r in feasible.resources_list:
                regions = cloud.regions_with_offering(
                    r.instance_type, r.accelerators, r.use_spot, r.region,
                    r.zone)
                for region in regions:
                    concrete = r.copy(region=region.name)
                    if not _resources_blocked(concrete, blocked_resources):
                        candidates.append(concrete)
        launchable[resources] = candidates
    if all(not v for v in launchable.values()):
        hint_str = ('\n'.join(f'  - {h}' for h in dict.fromkeys(hints))
                    if hints else '')
        fuzzy_str = (f'\nDid you mean: {sorted(set(all_fuzzy))[:6]}'
                     if all_fuzzy else '')
        raise exceptions.ResourcesUnavailableError(
            f'No launchable resource found for {task}.'
            + (f'\n{hint_str}' if hint_str else '') + fuzzy_str)
    return launchable, all_fuzzy


class Optimizer:
    """Chooses the best launchable Resources for every task in a DAG."""

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[
                     Set[resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        dag.validate()
        graph = dag.get_graph()
        import networkx as nx
        topo_order = list(nx.topological_sort(graph))
        if len(topo_order) > 12:
            raise exceptions.DagError(
                f'DAG with {len(topo_order)} tasks exceeds the optimizer '
                'bound (12).')

        # Per-task candidate metrics.
        per_task: Dict[task_lib.Task,
                       List[Tuple[resources_lib.Resources, float, float]]] = {}
        for task in topo_order:
            launchable, _ = _fill_in_launchable_resources(
                task, blocked_resources, quiet)
            cands: List[Tuple[resources_lib.Resources, float, float]] = []
            for _, rs in launchable.items():
                for r in rs:
                    hours = _estimate_runtime_hours(task, r)
                    cost = r.get_cost(hours * 3600) * task.num_nodes
                    cands.append((r, cost, hours))
            if not cands:
                raise exceptions.ResourcesUnavailableError(
                    f'No launchable resource found for {task} '
                    '(all candidates blocked).')
            # Keep candidates sorted by the objective.
            idx = 1 if minimize == OptimizeTarget.COST else 2
            cands.sort(key=lambda t: (t[idx], t[1], repr(t[0])))
            per_task[task] = cands

        def _egress_cost(src_task: 'task_lib.Task',
                         src: resources_lib.Resources,
                         dst: resources_lib.Resources) -> float:
            # Egress is priced on the data the *source* task produces
            # (reference optimizer.py:77-109).
            gigabytes = src_task.estimated_outputs_size_gb or 0
            if gigabytes <= 0 or src.cloud is None or dst.cloud is None:
                return 0.0
            if src.cloud.is_same_cloud(dst.cloud):
                return 0.0
            return src.cloud.get_egress_cost(gigabytes)

        objective_idx = 1 if minimize == OptimizeTarget.COST else 2
        if dag.is_chain() or len(topo_order) == 1:
            # DP over the chain with egress cost between stages
            # (optimizer.py:411).
            best_plan = Optimizer._optimize_chain(
                topo_order, per_task, _egress_cost, objective_idx)
        else:
            best_plan = Optimizer._optimize_general(
                graph, topo_order, per_task, _egress_cost, objective_idx)

        for task, (resources, cost, hours) in best_plan.items():
            task.best_resources = resources
        if not quiet:
            Optimizer.print_optimized_plan(topo_order, per_task, best_plan,
                                           minimize)
        return dag

    @staticmethod
    def _optimize_chain(
        topo_order, per_task, egress_cost_fn, objective_idx
    ) -> Dict['task_lib.Task', Tuple[resources_lib.Resources, float, float]]:
        # dp[candidate_index] = (total_objective, plan_so_far)
        prev_dp: List[Tuple[float, Dict]] = [(0.0, {})]
        prev_cands: List[Optional[Tuple]] = [None]
        prev_task: Optional['task_lib.Task'] = None
        for task in topo_order:
            cands = per_task[task]
            new_dp: List[Tuple[float, Dict]] = []
            for cand in cands:
                best_total, best_plan = None, None
                for (ptotal, pplan), pcand in zip(prev_dp, prev_cands):
                    egress = 0.0
                    if pcand is not None and prev_task is not None:
                        egress = egress_cost_fn(prev_task, pcand[0], cand[0])
                    total = ptotal + cand[objective_idx] + egress
                    if best_total is None or total < best_total:
                        best_total = total
                        best_plan = {**pplan, task: cand}
                new_dp.append((best_total, best_plan))
            prev_dp = new_dp
            prev_cands = [c for c in cands]
            prev_task = task
        best = min(prev_dp, key=lambda t: t[0])
        return best[1]

    # Backstop for adversarial inputs: expansions beyond this return
    # the best incumbent (a valid, near-optimal plan) with a warning
    # instead of hanging the client.  Never hit by realistic DAGs —
    # the admissible bound prunes wide diamonds to a tiny tree.
    _MAX_BNB_EXPANSIONS = 2_000_000

    @staticmethod
    def _optimize_general(
        graph, topo_order, per_task, egress_cost_fn, objective_idx
    ) -> Dict['task_lib.Task', Tuple[resources_lib.Resources, float, float]]:
        """Exact branch-and-bound over candidate assignments for
        general DAGs — optimal like the reference's pulp ILP
        (optimizer.py:472) without the solver dependency.

        Tasks are assigned in topo order; a partial assignment is
        pruned when its cost plus an ADMISSIBLE lower bound on the
        rest (each unassigned task's cheapest candidate + the cheapest
        possible egress for every edge into an unassigned task —
        egress >= 0, so the bound never overestimates) cannot beat the
        incumbent.  Candidates are explored cheapest-first so a good
        incumbent lands immediately and wide diamond DAGs prune to
        near-linear work.  No candidate truncation: the returned plan
        is provably optimal (unless the expansion backstop trips,
        which is logged).
        """
        tasks = list(topo_order)
        n = len(tasks)
        index = {t: i for i, t in enumerate(tasks)}
        # Candidates ascending by objective -> good incumbents early.
        cands = [sorted(per_task[t], key=lambda c: c[objective_idx])
                 for t in tasks]
        # Edges grouped by the consumer (always the LATER endpoint in
        # topo order): the edge's cost is added the moment the
        # consumer is assigned, with the producer already fixed.
        in_edges: List[List[Tuple[int, 'task_lib.Task']]] = [
            [] for _ in range(n)]
        for u, v in graph.edges:
            in_edges[index[v]].append((index[u], u))
        # Suffix bound: sum of cheapest candidates for tasks i..n-1.
        suffix_min = [0.0] * (n + 1)
        for i in range(n - 1, -1, -1):
            suffix_min[i] = suffix_min[i + 1] + \
                cands[i][0][objective_idx]

        best_total: Optional[float] = None
        best_choice: List[int] = []
        choice = [0] * n
        expansions = 0
        capped = False

        # Explicit-stack DFS: depth == number of tasks, so Python's
        # recursion limit (~1000) would trip on adversarial DAGs long
        # before the expansion backstop does.  Each frame keeps its
        # live candidate iterator, so resuming after a descend picks
        # up exactly where the loop left off.
        stack: List[Tuple[int, Any, float]] = []
        if n:
            stack.append((0, iter(enumerate(cands[0])), 0.0))
        while stack and not capped:
            i, cand_iter, partial = stack[-1]
            descended = False
            for ci, cand in cand_iter:
                expansions += 1
                if expansions > Optimizer._MAX_BNB_EXPANSIONS:
                    capped = True
                    break
                cost = partial + cand[objective_idx]
                for j, producer in in_edges[i]:
                    cost += egress_cost_fn(
                        producer, cands[j][choice[j]][0], cand[0])
                # Admissible bound on the remainder (egress >= 0).
                if best_total is not None and \
                        cost + suffix_min[i + 1] >= best_total:
                    # Candidates are sorted: every later candidate's
                    # node cost is >= this one's, but its egress may
                    # be smaller — only skip THIS candidate.
                    continue
                choice[i] = ci
                if i + 1 == n:
                    if best_total is None or cost < best_total:
                        best_total = cost
                        best_choice = list(choice)
                    continue
                stack.append((i + 1,
                              iter(enumerate(cands[i + 1])), cost))
                descended = True
                break
            if not descended and not capped:
                choice[i] = 0
                stack.pop()
        if capped:
            logger.warning(
                'optimizer: branch-and-bound expansion cap '
                f'({Optimizer._MAX_BNB_EXPANSIONS}) reached; the plan '
                'is the best found so far and may be suboptimal.')
        assert best_total is not None and best_choice
        return {t: cands[i][best_choice[i]]
                for i, t in enumerate(tasks)}

    @staticmethod
    def print_optimized_plan(topo_order, per_task, best_plan,
                             minimize) -> None:
        rows = []
        for task in topo_order:
            chosen, cost, hours = best_plan[task]
            spec = chosen.tpu_slice
            infra = f'{chosen.cloud} ({chosen.region})'
            acc = '-'
            if chosen.accelerators:
                (name, cnt), = chosen.accelerators.items()
                acc = name if cnt == 1 else f'{name}:{cnt}'
                if spec is not None:
                    acc += f' [{spec.num_hosts} host' + \
                        ('s]' if spec.num_hosts > 1 else ']')
            rows.append((str(task), infra, chosen.instance_type or '-', acc,
                         'spot' if chosen.use_spot else 'on-demand',
                         f'${cost:.2f}', f'{hours:.2f}h'))
        headers = ('TASK', 'INFRA', 'INSTANCE', 'ACCELERATORS', 'PRICING',
                   'EST. COST', 'EST. TIME')
        widths = [max(len(h), *(len(r[i]) for r in rows))
                  for i, h in enumerate(headers)]
        lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
        for r in rows:
            lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
        logger.info('Optimizer plan:\n' + '\n'.join(lines))


def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[Set[resources_lib.Resources]] = None,
             quiet: bool = False) -> dag_lib.Dag:
    return Optimizer.optimize(dag, minimize, blocked_resources, quiet)
