"""User-facing cluster/job operations.

Counterpart of the reference's sky/core.py:1-925 plus the status-refresh
reconciliation from sky/backends/backend_utils.py:2208-2612: cloud truth
(provision.query_instances) is reconciled against the client DB under a
per-cluster lock, detecting externally-changed state (preempted TPU
slices, manually deleted VMs, autostopped clusters).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu import usage
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.backend import tpu_gang_backend
from skypilot_tpu.provision import api as provision_api
from skypilot_tpu.utils import paths
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

ClusterStatus = global_user_state.ClusterStatus


def _backend(handle: Optional['backend_lib.ClusterHandle'] = None
             ) -> 'backend_lib.Backend':
    """Backend for a cluster handle: gang backend for cloud clusters,
    the docker backend for locally containerized ones."""
    if getattr(handle, 'provider_name', None) == 'local_docker':
        from skypilot_tpu.backend import docker_backend
        return docker_backend.LocalDockerBackend()
    return tpu_gang_backend.TpuGangBackend()


def _get_record_or_raise(cluster_name: str) -> Dict[str, Any]:
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record


# ---------------------------------------------------------------------------
# status (+ refresh reconciliation)
# ---------------------------------------------------------------------------
def refresh_cluster_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    """Reconcile one cluster's DB state with cloud truth (reference
    backend_utils.refresh_cluster_record, :2208)."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle: backend_lib.ClusterHandle = record['handle']
    if handle.provider_name == 'local_docker':
        return _refresh_docker_record(cluster_name, record, handle)
    lock = timeline.FileLockEvent(
        f'{paths.locks_dir()}/{cluster_name}.refresh.lock', timeout=20)
    try:
        with lock:
            try:
                statuses = provision_api.query_instances(
                    handle.provider_name, handle.cluster_name_on_cloud,
                    handle.provider_config, non_terminated_only=False)
            except Exception as e:  # noqa: BLE001
                logger.debug(f'query_instances failed for {cluster_name}: '
                             f'{e}; keeping cached status.')
                return record
            live = [s for s in statuses.values()
                    if s not in (None, 'terminated')]
            all_running = (len(live) >= handle.launched_nodes and
                           all(s == 'running' for s in live))
            any_stopped = any(s in ('stopped', 'stopping') for s in live)
            if not live:
                # Everything terminated externally (e.g. preempted TPU
                # slice): drop the record — TPU VMs cannot resume.
                global_user_state.remove_cluster(cluster_name,
                                                 terminate=True)
                return None
            if all_running:
                new_status = ClusterStatus.UP
            elif any_stopped:
                new_status = ClusterStatus.STOPPED
            else:
                new_status = ClusterStatus.INIT
            if new_status != record['status']:
                global_user_state.update_cluster_status(cluster_name,
                                                        new_status)
                record = global_user_state.get_cluster_from_name(
                    cluster_name)
            return record
    except TimeoutError:
        return record


def _refresh_docker_record(cluster_name: str, record: Dict[str, Any],
                           handle: 'backend_lib.ClusterHandle'
                           ) -> Optional[Dict[str, Any]]:
    """Docker-substrate reconciliation: container state is cloud truth."""
    from skypilot_tpu.backend import docker_backend
    backend = docker_backend.LocalDockerBackend()
    if not docker_backend.docker_available():
        return record  # can't query; keep cached status
    state = backend.query_status(handle)
    if state is None:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    new_status = (ClusterStatus.UP if state == 'running'
                  else ClusterStatus.STOPPED)
    if new_status != record['status']:
        global_user_state.update_cluster_status(cluster_name, new_status)
        record = global_user_state.get_cluster_from_name(cluster_name)
    return record


@usage.entrypoint('sky.status')
def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records, optionally reconciled against cloud truth
    (reference core.status / `sky status -r`)."""
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        wanted = set(cluster_names)
        records = [r for r in records if r['name'] in wanted]
    if refresh:
        refreshed = []
        for record in records:
            updated = refresh_cluster_record(record['name'])
            if updated is not None:
                refreshed.append(updated)
        records = refreshed
    return records


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------
@usage.entrypoint('sky.start')
def start(cluster_name: str, retry_until_up: bool = False) -> None:
    """Restart a STOPPED cluster (reference core.start; provisioner
    resume_stopped_nodes, provision/provisioner.py:131)."""
    record = _get_record_or_raise(cluster_name)
    if record['status'] == ClusterStatus.UP:
        logger.info(f'Cluster {cluster_name!r} is already UP.')
        return
    handle: backend_lib.ClusterHandle = record['handle']
    from skypilot_tpu import task as task_lib
    dummy = task_lib.Task(cluster_name + '-start')
    dummy.num_nodes = handle.launched_nodes
    dummy.set_resources(handle.launched_resources)
    dummy.best_resources = handle.launched_resources
    _backend(handle).provision(dummy, handle.launched_resources, dryrun=False,
                         stream_logs=True, cluster_name=cluster_name,
                         retry_until_up=retry_until_up)


@usage.entrypoint('sky.stop')
def stop(cluster_name: str) -> None:
    record = _get_record_or_raise(cluster_name)
    handle = record['handle']
    _backend(handle).teardown(handle, terminate=False)


@usage.entrypoint('sky.down')
def down(cluster_name: str, purge: bool = False) -> None:
    record = _get_record_or_raise(cluster_name)
    handle = record['handle']
    _backend(handle).teardown(handle, terminate=True, purge=purge)


@usage.entrypoint('sky.autostop')
def autostop(cluster_name: str, idle_minutes: int,
             down: bool = False) -> None:  # pylint: disable=redefined-outer-name
    record = _get_record_or_raise(cluster_name)
    _backend(record['handle']).set_autostop(record['handle'], idle_minutes, down)


@usage.entrypoint('sky.endpoints')
def endpoints(cluster_name: str,
              port: Optional[int] = None) -> Dict[str, List[str]]:
    """Externally reachable URL(s) for a cluster's opened ports
    (reference core.endpoints, sky/core.py:189).

    Most clouds expose ports on the head's public IP; kubernetes
    resolves through its LB/NodePort service.  Returns {} when the
    endpoint is not (yet) assigned — e.g. a LoadBalancer still
    pending."""
    from skypilot_tpu.provision import api as provision_api
    record = _get_record_or_raise(cluster_name)
    handle = record['handle']
    from skypilot_tpu.provision import common as provision_common
    declared = list(getattr(handle.launched_resources, 'ports', None)
                    or [])
    if not declared:
        raise exceptions.NotSupportedError(
            f'Cluster {cluster_name!r} has no opened ports; launch '
            f'with `--ports` to expose one.')
    if port is not None:
        if port not in provision_common.expand_ports(declared):
            raise exceptions.NotSupportedError(
                f'Port {port} was not opened on {cluster_name!r} '
                f'(declared: {declared}).')
        ports = [str(port)]
    else:
        ports = declared
    head = handle.head_address
    if head.startswith('local:'):
        head_ip = '127.0.0.1'
    elif ':' in head:  # k8s:/docker: scheme address — no direct IP
        head_ip = None
    else:
        head_ip = head
    return provision_api.query_ports(
        handle.provider_name, handle.cluster_name_on_cloud, ports,
        head_ip=head_ip, provider_config=handle.provider_config)


# ---------------------------------------------------------------------------
# jobs
# ---------------------------------------------------------------------------
@usage.entrypoint('sky.queue')
def queue(cluster_name: str) -> List[Dict[str, Any]]:
    record = _get_record_or_raise(cluster_name)
    return _backend(record['handle']).get_job_queue(record['handle'])


@usage.entrypoint('sky.cancel')
def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    record = _get_record_or_raise(cluster_name)
    return _backend(record['handle']).cancel_jobs(record['handle'], job_ids, all_jobs)


@usage.entrypoint('sky.tail_logs')
def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0) -> int:
    record = _get_record_or_raise(cluster_name)
    return _backend(record['handle']).tail_logs(record['handle'], job_id, follow, tail)


@usage.entrypoint('sky.job_status')
def job_status(cluster_name: str, job_ids: Optional[List[int]] = None
               ) -> Dict[int, Optional[str]]:
    record = _get_record_or_raise(cluster_name)
    if job_ids is None:
        jobs = _backend(record['handle']).get_job_queue(record['handle'])
        job_ids = [j['job_id'] for j in jobs[:1]]
    return _backend(record['handle']).get_job_status(record['handle'], job_ids)


@usage.entrypoint('sky.download_logs')
def download_logs(cluster_name: str, job_ids: Optional[List[int]] = None,
                  local_dir: Optional[str] = None) -> Dict[int, str]:
    """Rsync job log dirs back to the client (reference
    `sky logs --sync-down`)."""
    import os
    record = _get_record_or_raise(cluster_name)
    handle: backend_lib.ClusterHandle = record['handle']
    backend = _backend(handle)
    if job_ids is None:
        jobs = backend.get_job_queue(handle)
        job_ids = [j['job_id'] for j in jobs]
    out: Dict[int, str] = {}
    local_root = local_dir or os.path.join(paths.logs_dir(), cluster_name)
    from skypilot_tpu.backend import command_runner as runner_lib
    head = runner_lib.CommandRunner.from_address(
        handle.head_address, ssh_user=handle.ssh_user,
        ssh_key=handle.ssh_key)
    for job_id in job_ids:
        remote_dir = (f'{handle.head_agent_root or "~"}/'
                      f'.skytpu_agent/job_logs/job_{job_id}')
        local_path = os.path.join(local_root, f'job_{job_id}')
        os.makedirs(local_path, exist_ok=True)
        if isinstance(head, runner_lib.LocalHostRunner):
            head.rsync(f'.skytpu_agent/job_logs/job_{job_id}', local_path,
                       up=False)
        else:
            head.rsync(remote_dir, local_path, up=False)
        out[job_id] = local_path
    return out


# ---------------------------------------------------------------------------
# cost report
# ---------------------------------------------------------------------------
@usage.entrypoint('sky.cost_report')
def cost_report() -> List[Dict[str, Any]]:
    """Accumulated cost per cluster from usage intervals (reference
    core.cost_report + global_user_state.py:469-525)."""
    out = []
    for record in global_user_state.get_cluster_history():
        resources = record['launched_resources']
        duration = 0
        now = int(time.time())
        for start_t, end_t in record['usage_intervals']:
            duration += (end_t if end_t is not None else now) - start_t
        cost = None
        if resources is not None and resources.is_launchable():
            try:
                cost = resources.get_cost(duration) * \
                    (record['num_nodes'] or 1)
            except Exception:  # noqa: BLE001 — catalog drift
                cost = None
        out.append({
            'name': record['name'],
            'resources': resources,
            'num_nodes': record['num_nodes'],
            'duration_seconds': duration,
            'cost': cost,
            'still_exists': record['still_exists'],
        })
    return out


# ---------------------------------------------------------------------------
# storage
# ---------------------------------------------------------------------------
@usage.entrypoint('sky.storage_ls')
def storage_ls() -> List[Dict[str, Any]]:
    return global_user_state.get_storage()


@usage.entrypoint('sky.storage_delete')
def storage_delete(name: str) -> None:
    handle = global_user_state.get_handle_from_storage_name(name)
    if handle is None:
        raise exceptions.StorageError(f'Storage {name!r} not found.')
    from skypilot_tpu.data import storage as storage_lib
    storage_obj = storage_lib.Storage.from_handle(handle)
    storage_obj.delete()
    global_user_state.remove_storage(name)
