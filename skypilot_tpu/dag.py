"""Task DAGs.

Counterpart of the reference's sky/dag.py:1-106: a thin networkx DiGraph of
Tasks with a thread-local "current dag" context so `with Dag() as dag:` plus
the `Task.__rshift__` operator build pipelines.  Only single-task DAGs are
executed directly (reference sky/execution.py:181); chain DAGs are consumed
by the managed-jobs pipeline runner.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import networkx as nx

from skypilot_tpu import exceptions


class Dag:
    """Directed acyclic graph of Tasks."""

    def __init__(self) -> None:
        self.graph = nx.DiGraph()
        self.name: Optional[str] = None
        self.policy_applied: bool = False

    @property
    def tasks(self) -> List['task_lib.Task']:
        return list(self.graph.nodes)

    def add(self, task) -> None:
        self.graph.add_node(task)

    def remove(self, task) -> None:
        self.graph.remove_node(task)

    def add_edge(self, op1, op2) -> None:
        assert op1 in self.graph.nodes
        assert op2 in self.graph.nodes
        self.graph.add_edge(op1, op2)

    def __len__(self) -> int:
        return len(self.graph.nodes)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *args) -> None:
        pop_dag()

    def __repr__(self) -> str:
        pformat = ', '.join(repr(t) for t in self.tasks)
        return f'DAG:\n {pformat}'

    def get_graph(self) -> nx.DiGraph:
        return self.graph

    def is_chain(self) -> bool:
        """True iff the DAG is a linear chain (reference sky/dag.py:60)."""
        nodes = list(self.graph.nodes)
        out_degrees = [self.graph.out_degree(n) for n in nodes]
        in_degrees = [self.graph.in_degree(n) for n in nodes]
        return (len(nodes) <= 1 or
                (all(d <= 1 for d in out_degrees) and
                 all(d <= 1 for d in in_degrees) and
                 sum(d == 0 for d in out_degrees) == 1 and
                 sum(d == 0 for d in in_degrees) == 1))

    def validate(self) -> None:
        if not nx.is_directed_acyclic_graph(self.graph):
            raise exceptions.DagError('DAG has a cycle.')
        for task in self.tasks:
            task.validate()


class _DagContext(threading.local):
    """Thread-local stack of active dags (reference sky/dag.py:75-106)."""

    def __init__(self) -> None:
        super().__init__()
        self._stack: List[Dag] = []

    def push(self, dag: Dag) -> None:
        self._stack.append(dag)

    def pop(self) -> Dag:
        return self._stack.pop()

    def current(self) -> Optional[Dag]:
        return self._stack[-1] if self._stack else None


_dag_context = _DagContext()
push_dag = _dag_context.push
pop_dag = _dag_context.pop
get_current_dag = _dag_context.current
