"""Observability: dependency-free metrics + per-request traces.

`metrics` holds the thread-safe Counter/Gauge/Histogram primitives, the
process-global `Registry`, and Prometheus text exposition; `tracing`
holds `RequestTrace`/`TraceStore` for per-request lifecycle timelines.
Both are pure stdlib so they can be imported from any layer (engine,
server, trainer, bench) without dragging in JAX.
"""
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.observability.metrics import (CONTENT_TYPE_LATEST, Counter,
                                                Gauge, Histogram, Registry,
                                                get_registry)
from skypilot_tpu.observability.tracing import RequestTrace, TraceStore

__all__ = [
    'CONTENT_TYPE_LATEST',
    'Counter',
    'Gauge',
    'Histogram',
    'Registry',
    'RequestTrace',
    'TraceStore',
    'get_registry',
    'metrics',
    'tracing',
]
