"""Observability: dependency-free metrics + per-request traces.

`metrics` holds the thread-safe Counter/Gauge/Histogram primitives, the
process-global `Registry`, and Prometheus text exposition; `tracing`
holds `RequestTrace`/`TraceStore` for per-request lifecycle timelines;
`ledger` holds `StepLedger`, the bounded per-step performance ring with
roofline/MFU attribution.  All are pure stdlib so they can be imported
from any layer (engine, server, trainer, bench) without dragging in
JAX.
"""
import re

from skypilot_tpu.observability import events
from skypilot_tpu.observability import ledger
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.observability.events import EVENT_CONTRACT, EventRing
from skypilot_tpu.observability.ledger import StepLedger
from skypilot_tpu.observability.metrics import (CONTENT_TYPE_LATEST, Counter,
                                                Gauge, Histogram, Registry,
                                                get_registry)
from skypilot_tpu.observability.tracing import (TRACE_HEADER, RequestTrace,
                                                Span, SpanStore, TraceStore,
                                                format_trace_context,
                                                parse_trace_context)

# Naming contract for every series the repo registers.  Type-suffix
# conventions (Counter -> _total, Histogram -> _seconds/_bytes, or
# _tokens for count-valued histograms like the speculative accepted
# length) are asserted by tests/unit_tests/test_observability.py on
# top of this.
METRIC_NAME_RE = re.compile(
    r'skytpu_[a-z0-9_]+')

# The single source of truth for metric names: the exposition tests,
# dashboards, and the skylint metric-contract rule all key off this
# set.  Registering a series whose name is absent here fails tier-1
# (tests + skylint), so add the name in the same PR that adds the
# series.
METRIC_CONTRACT = frozenset({
    # infer/engine.py — serving lifecycle
    'skytpu_admission_backpressure_total',
    'skytpu_decode_batch_occupancy_ratio',
    'skytpu_decode_cache_read_bytes',
    'skytpu_decode_kernel_steps_total',   # labels: path=fused|xla
    'skytpu_decode_live_slots',
    'skytpu_decode_queue_depth',
    'skytpu_decode_slot_steps_total',
    'skytpu_decode_steps_total',
    'skytpu_kv_free_pages',
    'skytpu_kv_pages_cannibalized_total',
    'skytpu_output_tokens_total',
    'skytpu_prefix_cache_page_hits_total',
    'skytpu_prefix_cache_page_misses_total',
    # infer/engine.py — chunked prefill (dedicated ticks and the
    # mixed-batch path behind --prefill-mix-budget)
    'skytpu_prefill_cache_read_bytes',
    'skytpu_prefill_kernel_steps_total',  # labels: path=fused|xla
    'skytpu_prefill_mix_tokens_total',
    'skytpu_prefill_mixed_steps_total',
    'skytpu_prompt_tokens_total',
    # infer/speculative.py — speculative decoding (registered only on
    # engines started with spec_k > 0; the replica scrape test filters
    # the prefix out for plain servers)
    'skytpu_spec_steps_total',
    'skytpu_spec_draft_steps_total',
    'skytpu_spec_proposed_tokens_total',
    'skytpu_spec_accepted_tokens_total',
    'skytpu_spec_accepted_tokens',
    # infer/engine.py + infer/handoff.py — disaggregated prefill/decode
    # (registered only on engines started with role != 'both'; a plain
    # replica's scrape must not advertise them)
    'skytpu_handoff_export_seconds',      # serialize KV -> wire artifact
    'skytpu_handoff_admit_seconds',       # wire artifact -> live slot
    'skytpu_handoff_bytes',               # labels: form=wire|raw (zlib)
    'skytpu_handoff_requests_total',      # labels: side=export|admit
    'skytpu_handoff_pages_total',         # labels: kind=shipped|deduped
    # infer/engine.py + infer/fleet_cache.py — fleet-tiered prefix
    # cache (registered only on engines started with host_cache_bytes
    # > 0; a tier-less replica's scrape must not advertise them)
    'skytpu_fleet_cache_hits_total',
    'skytpu_fleet_cache_misses_total',
    'skytpu_fleet_cache_spilled_pages_total',
    'skytpu_fleet_cache_spilled_bytes_total',
    'skytpu_fleet_cache_evicted_pages_total',
    'skytpu_fleet_cache_rehydrated_pages_total',
    'skytpu_fleet_cache_reprefill_tokens_saved_total',
    'skytpu_fleet_cache_stored_bytes',
    'skytpu_fleet_cache_stored_pages',
    # infer/engine.py — live mid-generation migration (registered
    # lazily on first migrate activity: ANY role can drain or admit)
    'skytpu_migration_requests_total',    # labels: side=out|in
    'skytpu_migration_export_seconds',    # slot checkpoint -> artifact
    'skytpu_migration_admit_seconds',     # artifact -> resumed slot
    'skytpu_migration_bytes',             # labels: form=wire|raw
    'skytpu_request_queue_seconds',
    'skytpu_request_tpot_seconds',
    'skytpu_request_ttft_seconds',
    'skytpu_request_deadline_expired_total',
    'skytpu_requests_aborted_total',
    'skytpu_requests_cancelled_total',
    'skytpu_requests_evicted_total',
    'skytpu_requests_finished_total',
    'skytpu_requests_in_flight',
    'skytpu_requests_submitted_total',
    # infer/engine.py + train/trainer.py — runtime (compile/retrace,
    # host-step breakdown, memory watermarks); see the "Fleet
    # observability" section of docs/architecture.md for semantics
    'skytpu_jit_compiles_total',          # labels: fn=decode|prefill|train_step
    'skytpu_jit_compile_seconds',         # compile (first-call) wall time
    'skytpu_step_dispatch_seconds',       # enqueue wall time, cache-hit steps
    'skytpu_step_device_wait_seconds',    # scheduler blocked on step results
    'skytpu_step_host_overlap_seconds',   # host work hidden behind device step
    'skytpu_step_mfu',                    # achieved MFU of the last committed step
    'skytpu_model_flops_per_token',       # analytic fwd FLOPs/token at live ctx
    'skytpu_pipeline_depth',              # in-flight decode steps (async: 0/1)
    'skytpu_mesh_devices',                # devices in the engine mesh (1 = unsharded)
    'skytpu_decode_collective_seconds',   # sharded-step wait (collectives bound)
    'skytpu_kv_pages_used_peak',          # page-pool high-watermark
    'skytpu_device_memory_peak_bytes',    # device allocator high-watermark
    # infer/engine.py — SLO accounting (targets via SKYTPU_SLO_TTFT_S /
    # SKYTPU_SLO_TPOT_S; zero/unset disables)
    'skytpu_slo_requests_total',          # labels: slo=ttft|tpot, result=good|violated
    'skytpu_slo_burn_rate',               # labels: slo; set by router /fleet/slo
    # infer/server.py — HTTP surface + failure containment
    'skytpu_decode_loop_restarts_total',
    'skytpu_decode_stalls_detected_total',
    'skytpu_health_state',
    'skytpu_http_request_seconds',
    'skytpu_http_requests_total',
    'skytpu_requests_shed_total',
    # observability/events.py — flight recorder
    'skytpu_events_total',                # labels: kind (EVENT_CONTRACT)
    # utils/chaos.py — fault injection
    'skytpu_chaos_injections_total',
    # serve/router.py + serve/replica_supervisor.py — the self-healing
    # serving data plane
    'skytpu_router_affinity_total',
    'skytpu_router_circuit_transitions_total',
    'skytpu_router_desired_replicas',
    'skytpu_router_failovers_total',
    'skytpu_router_health_probes_total',
    'skytpu_router_replica_restarts_total',
    'skytpu_router_replicas_routable',
    'skytpu_router_replicas_total',
    'skytpu_router_request_seconds',
    'skytpu_router_requests_total',
    'skytpu_router_retries_total',
    'skytpu_router_scale_events_total',
    'skytpu_router_signal_age_seconds',   # labels: replica; scrape age
    # serve/router.py — fleet federation (GET /fleet/metrics scrape)
    'skytpu_fleet_replicas_routable',     # routable replicas at scrape time
    'skytpu_fleet_free_pages',            # sum of free KV pages fleet-wide
    'skytpu_fleet_queue_depth',           # sum of replica queue depths
    'skytpu_fleet_scrape_seconds',        # one federated scrape, wall time
    # train/trainer.py — training loop
    'skytpu_train_step_seconds',
    'skytpu_train_steps_total',
    'skytpu_train_tokens_per_sec',
    'skytpu_train_tokens_total',
})

__all__ = [
    'EVENT_CONTRACT',
    'METRIC_CONTRACT',
    'METRIC_NAME_RE',
    'CONTENT_TYPE_LATEST',
    'TRACE_HEADER',
    'Counter',
    'EventRing',
    'Gauge',
    'Histogram',
    'Registry',
    'RequestTrace',
    'Span',
    'SpanStore',
    'StepLedger',
    'TraceStore',
    'events',
    'ledger',
    'format_trace_context',
    'get_registry',
    'metrics',
    'parse_trace_context',
    'tracing',
]
