"""Observability: dependency-free metrics + per-request traces.

`metrics` holds the thread-safe Counter/Gauge/Histogram primitives, the
process-global `Registry`, and Prometheus text exposition; `tracing`
holds `RequestTrace`/`TraceStore` for per-request lifecycle timelines.
Both are pure stdlib so they can be imported from any layer (engine,
server, trainer, bench) without dragging in JAX.
"""
import re

from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.observability.metrics import (CONTENT_TYPE_LATEST, Counter,
                                                Gauge, Histogram, Registry,
                                                get_registry)
from skypilot_tpu.observability.tracing import RequestTrace, TraceStore

# Naming contract for every series the repo registers.  Type-suffix
# conventions (Counter -> _total, Histogram -> _seconds/_bytes) are
# asserted by tests/unit_tests/test_observability.py on top of this.
METRIC_NAME_RE = re.compile(
    r'skytpu_[a-z0-9_]+')

# The single source of truth for metric names: the exposition tests,
# dashboards, and the skylint metric-contract rule all key off this
# set.  Registering a series whose name is absent here fails tier-1
# (tests + skylint), so add the name in the same PR that adds the
# series.
METRIC_CONTRACT = frozenset({
    # infer/engine.py — serving lifecycle
    'skytpu_admission_backpressure_total',
    'skytpu_decode_batch_occupancy_ratio',
    'skytpu_decode_cache_read_bytes',
    'skytpu_decode_live_slots',
    'skytpu_decode_queue_depth',
    'skytpu_decode_slot_steps_total',
    'skytpu_decode_steps_total',
    'skytpu_kv_free_pages',
    'skytpu_kv_pages_cannibalized_total',
    'skytpu_output_tokens_total',
    'skytpu_prefix_cache_page_hits_total',
    'skytpu_prefix_cache_page_misses_total',
    'skytpu_prompt_tokens_total',
    'skytpu_request_queue_seconds',
    'skytpu_request_tpot_seconds',
    'skytpu_request_ttft_seconds',
    'skytpu_request_deadline_expired_total',
    'skytpu_requests_aborted_total',
    'skytpu_requests_cancelled_total',
    'skytpu_requests_evicted_total',
    'skytpu_requests_finished_total',
    'skytpu_requests_in_flight',
    'skytpu_requests_submitted_total',
    # infer/server.py — HTTP surface + failure containment
    'skytpu_decode_loop_restarts_total',
    'skytpu_decode_stalls_detected_total',
    'skytpu_health_state',
    'skytpu_http_request_seconds',
    'skytpu_http_requests_total',
    'skytpu_requests_shed_total',
    # utils/chaos.py — fault injection
    'skytpu_chaos_injections_total',
    # serve/router.py + serve/replica_supervisor.py — the self-healing
    # serving data plane
    'skytpu_router_affinity_total',
    'skytpu_router_circuit_transitions_total',
    'skytpu_router_desired_replicas',
    'skytpu_router_failovers_total',
    'skytpu_router_health_probes_total',
    'skytpu_router_replica_restarts_total',
    'skytpu_router_replicas_routable',
    'skytpu_router_replicas_total',
    'skytpu_router_request_seconds',
    'skytpu_router_requests_total',
    'skytpu_router_retries_total',
    'skytpu_router_scale_events_total',
    # train/trainer.py — training loop
    'skytpu_train_step_seconds',
    'skytpu_train_steps_total',
    'skytpu_train_tokens_per_sec',
    'skytpu_train_tokens_total',
})

__all__ = [
    'METRIC_CONTRACT',
    'METRIC_NAME_RE',
    'CONTENT_TYPE_LATEST',
    'Counter',
    'Gauge',
    'Histogram',
    'Registry',
    'RequestTrace',
    'TraceStore',
    'get_registry',
    'metrics',
    'tracing',
]
