"""Dependency-free metrics core: Counter / Gauge / Histogram + Registry.

Design goals, in order:

1. **Zero third-party deps.**  The serving container must not grow a
   `prometheus_client` requirement; exposition is ~100 lines of text
   formatting (Prometheus text format v0.0.4).
2. **Cheap on the hot path.**  The continuous-batching decode loop
   publishes ~10 samples per step.  Every update is a dict lookup plus
   a float add under a per-metric lock — no string formatting, no
   allocation beyond the first `labels()` call for a given label set.
   A disabled registry short-circuits updates to a single attribute
   read so the overhead-guard bench can diff enabled vs. disabled.
3. **Get-or-create registration.**  Tests (and the engine) construct
   many engines per process against the process-global registry;
   re-registering an identical metric returns the existing object,
   while a type conflict raises.

Naming contract (enforced by a tier-1 guard test): every metric this
codebase registers matches

    ^skytpu_[a-z0-9_]+(_total|_bytes|_seconds|_ratio|_count)?$

i.e. snake_case with conventional unit suffixes.  The registry itself
only enforces Prometheus-legal names so the module stays generic.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_METRIC_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
_LABEL_NAME_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*$')

# Per-metric cap on distinct label sets.  Beyond it, new label sets
# collapse into a single overflow child so a buggy caller (e.g. a
# request id used as a label) cannot grow memory without bound.
DEFAULT_MAX_LABEL_SETS = 64
_OVERFLOW_LABEL_VALUE = '_overflow'

# Latency buckets (seconds): 1ms .. 10min, roughly 2.5x steps.
DEFAULT_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                           300.0, 600.0)
# Byte-size buckets: 4 KiB .. 64 GiB, powers of 4.
DEFAULT_BYTE_BUCKETS = tuple(float(4**i * 1024) for i in range(13))


def _fmt_value(v: float) -> str:
    """Prometheus-style float rendering: integers without exponents."""
    if v == math.inf:
        return '+Inf'
    if v == -math.inf:
        return '-Inf'
    if v != v:  # NaN
        return 'NaN'
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    return text.replace('\\', r'\\').replace('\n', r'\n')


def _escape_label_value(text: str) -> str:
    return text.replace('\\', r'\\').replace('\n', r'\n').replace('"', r'\"')


def _render_labels(labelnames: Sequence[str], labelvalues: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(labelnames, labelvalues)]
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ''
    inner = ','.join(
        f'{n}="{_escape_label_value(str(v))}"' for n, v in pairs)
    return '{' + inner + '}'


class Metric:
    """Base: a named family holding one child per label set."""

    TYPE = 'untyped'

    def __init__(self, registry: 'Registry', name: str, help_text: str,
                 labelnames: Sequence[str] = ()):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f'Invalid metric name: {name!r}')
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith('__'):
                raise ValueError(f'Invalid label name: {ln!r}')
        if 'le' in labelnames and self.TYPE == 'histogram':
            raise ValueError("Histogram label 'le' is reserved")
        self._registry = registry
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], 'Metric'] = {}
        self._overflow_logged = False
        # An unlabeled metric is its own (single) child.
        if not self.labelnames:
            self._init_child()

    # -- child state (overridden per type) -----------------------------
    def _init_child(self) -> None:
        raise NotImplementedError

    def _check_enabled(self) -> bool:
        return self._registry.enabled

    def _new_child(self) -> 'Metric':
        """Allocate an empty child sharing this family's identity/lock."""
        child = self.__class__.__new__(self.__class__)
        child._registry = self._registry
        child.name = self.name
        child.help = self.help
        child.labelnames = ()
        child._lock = self._lock
        child._children = {}
        child._overflow_logged = False
        self._copy_config(child)
        child._init_child()
        return child

    def _copy_config(self, child: 'Metric') -> None:
        """Copy type-specific config (e.g. buckets) onto a new child."""

    def labels(self, **labelvalues: str) -> 'Metric':
        """Return (creating if needed) the child for this label set."""
        if not self.labelnames:
            raise ValueError(f'{self.name} has no labels')
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f'{self.name} expects labels {self.labelnames}, '
                f'got {tuple(sorted(labelvalues))}')
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self._registry.max_label_sets:
                    key = (_OVERFLOW_LABEL_VALUE,) * len(self.labelnames)
                    child = self._children.get(key)
                    if not self._overflow_logged:
                        self._overflow_logged = True
                        logger.warning(
                            f'Metric {self.name} exceeded '
                            f'{self._registry.max_label_sets} label sets; '
                            'collapsing new series into '
                            f'{_OVERFLOW_LABEL_VALUE!r}')
                    if child is not None:
                        return child
                child = self._new_child()
                self._children[key] = child
        return child

    def _iter_children(self) -> Iterable[Tuple[Tuple[str, ...], 'Metric']]:
        # Snapshot under the lock, yield outside it: _render() needs to
        # re-acquire the (non-reentrant) family lock to read values.
        with self._lock:
            if not self.labelnames:
                items = [((), self)]
            else:
                items = [(k, self._children[k])
                         for k in sorted(self._children)]
        return items

    def collect(self) -> List[str]:
        lines = [
            f'# HELP {self.name} {_escape_help(self.help)}',
            f'# TYPE {self.name} {self.TYPE}',
        ]
        for key, child in self._iter_children():
            lines.extend(child._render(self.labelnames, key))
        return lines

    def _render(self, labelnames: Sequence[str],
                labelvalues: Sequence[str]) -> List[str]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing float."""

    TYPE = 'counter'

    def _init_child(self) -> None:
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError('Counter can only increase')
        if not self._check_enabled():
            return
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def value_for(self, **labelvalues: str) -> float:
        return self.labels(**labelvalues).value

    def _render(self, labelnames, labelvalues) -> List[str]:
        return [f'{self.name}{_render_labels(labelnames, labelvalues)} '
                f'{_fmt_value(self.value)}']


class Gauge(Metric):
    """Instantaneous value; can go up and down."""

    TYPE = 'gauge'

    def _init_child(self) -> None:
        self._value = 0.0

    def set(self, value: float) -> None:
        if not self._check_enabled():
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._check_enabled():
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def value_for(self, **labelvalues: str) -> float:
        return self.labels(**labelvalues).value

    def _render(self, labelnames, labelvalues) -> List[str]:
        return [f'{self.name}{_render_labels(labelnames, labelvalues)} '
                f'{_fmt_value(self.value)}']


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics: le = <=)."""

    TYPE = 'histogram'

    def __init__(self, registry, name, help_text, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError('Histogram needs at least one bucket')
        if b[-1] != math.inf:
            b.append(math.inf)
        self._buckets = tuple(b)
        super().__init__(registry, name, help_text, labelnames)

    def _init_child(self) -> None:
        self._bucket_counts = [0] * len(self._buckets)
        self._sum = 0.0
        self._count = 0

    def _copy_config(self, child: 'Metric') -> None:
        child._buckets = self._buckets  # type: ignore[attr-defined]

    def observe(self, value: float) -> None:
        if not self._check_enabled():
            return
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            # First bucket whose bound >= v; all later buckets are
            # cumulative at render time so only one slot is bumped.
            for i, bound in enumerate(self._buckets):
                if v <= bound:
                    self._bucket_counts[i] += 1
                    break

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def _render(self, labelnames, labelvalues) -> List[str]:
        with self._lock:
            counts = list(self._bucket_counts)
            total = self._count
            s = self._sum
        lines = []
        cum = 0
        for bound, c in zip(self._buckets, counts):
            cum += c
            le = _render_labels(labelnames, labelvalues,
                                extra=('le', _fmt_value(bound)))
            lines.append(f'{self.name}_bucket{le} {cum}')
        plain = _render_labels(labelnames, labelvalues)
        lines.append(f'{self.name}_sum{plain} {_fmt_value(s)}')
        lines.append(f'{self.name}_count{plain} {total}')
        return lines


class Registry:
    """A set of named metrics; renders Prometheus text format v0.0.4.

    One process-global instance (`get_registry()`) backs the engine,
    server, trainer and bench.  Tests and the overhead bench may build
    private registries; `enabled=False` turns every update into a
    near-free no-op while keeping the metric objects usable.
    """

    def __init__(self, enabled: bool = True,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self.enabled = enabled
        self.max_label_sets = max_label_sets
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def _get_or_create(self, cls, name, help_text, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f'Metric {name} already registered as '
                        f'{type(existing).__name__}, not {cls.__name__}')
                if tuple(labelnames) != existing.labelnames:
                    raise ValueError(
                        f'Metric {name} already registered with labels '
                        f'{existing.labelnames}, not {tuple(labelnames)}')
                return existing
            metric = cls(self, name, help_text, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str = '',
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = '',
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str = '',
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def expose(self) -> str:
        """Render every metric in Prometheus text format v0.0.4."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.collect())
        return '\n'.join(lines) + '\n' if lines else ''


CONTENT_TYPE_LATEST = 'text/plain; version=0.0.4; charset=utf-8'

# -- scrape-side parsing (the router's health loop consumes replica
# -- /metrics text; keeping the parser next to the renderer keeps the
# -- two in lock-step) -------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str],
                                                        ...], float]]:
    """Parse Prometheus text format v0.0.4 into
    ``{sample_name: {((label, value), ...): float}}``.

    Inverse of :meth:`Registry.expose` for the subset this repo
    renders; unparseable lines are skipped (a scrape consumer must
    survive a half-written exposition rather than raise).  Histogram
    samples appear under their ``_bucket``/``_sum``/``_count`` names.
    """
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group('value'))
        except ValueError:
            continue
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace('\\n', '\n')
             .replace('\\\\', '\\'))
            for k, v in _LABEL_RE.findall(m.group('labels') or '')))
        out.setdefault(m.group('name'), {})[labels] = value
    return out


def sample_value(parsed: Dict[str, Dict[Tuple[Tuple[str, str], ...],
                                        float]],
                 name: str, **labels: str) -> Optional[float]:
    """One sample's value from :func:`parse_exposition` output, or
    None when the series/label set is absent."""
    series = parsed.get(name)
    if not series:
        return None
    key = tuple(sorted((k, str(v)) for k, v in labels.items()))
    return series.get(key)


def histogram_quantile(parsed: Dict[str, Dict[Tuple[Tuple[str, str],
                                                    ...], float]],
                       name: str, q: float) -> Optional[float]:
    """Estimate quantile ``q`` from a scraped histogram's cumulative
    ``<name>_bucket`` samples (upper-bound estimate: the bound of the
    first bucket whose cumulative count reaches ``q * count``).
    Returns None with no observations; +Inf-bucket hits report the
    largest finite bound (the histogram cannot resolve beyond it)."""
    buckets = parsed.get(name + '_bucket')
    if not buckets:
        return None
    bounds: List[Tuple[float, float]] = []
    for labelset, value in buckets.items():
        le = dict(labelset).get('le')
        if le is None:
            continue
        bound = math.inf if le == '+Inf' else float(le)
        bounds.append((bound, value))
    if not bounds:
        return None
    bounds.sort()
    total = bounds[-1][1]
    if total <= 0:
        return None
    target = q * total
    largest_finite = max((b for b, _ in bounds if b != math.inf),
                         default=None)
    for bound, cum in bounds:
        if cum >= target:
            if bound == math.inf:
                return largest_finite
            return bound
    return largest_finite


_GLOBAL_REGISTRY = Registry()


def get_registry() -> Registry:
    """The process-global registry shared by engine/server/train/bench."""
    return _GLOBAL_REGISTRY
