"""Per-request lifecycle traces for the serving engines.

A `RequestTrace` records the host-side timeline of one request:

    queued -> admitted -> prefill chunk(s) -> first token -> decode
           -> finished | cancelled | evicted | aborted

and derives the latencies that matter for serving SLOs: queue wait,
TTFT (time to first token, measured from submit), and TPOT (mean
per-output-token latency over the decode phase).

The `TraceStore` keeps in-flight traces in a dict keyed by request id
plus a bounded ring of completed traces (newest last), and can mirror
every transition to a JSONL event sink for offline ingestion
(`SKYTPU_TRACE_JSONL=<path>`, read by the engines).  All methods are
thread-safe and O(1); nothing here touches JAX or device memory.
"""
from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Any, Dict, List, Optional

# Terminal states a trace can land in.
TERMINAL_STATES = ('finished', 'cancelled', 'evicted', 'aborted')


@dataclasses.dataclass
class RequestTrace:
    """Host-side timeline of one request (all timestamps time.time())."""
    request_id: int
    queued_ts: float
    prompt_tokens: int = 0
    http_request_id: Optional[str] = None
    state: str = 'queued'
    admitted_ts: Optional[float] = None
    prefill_chunks: int = 0
    prefill_done_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finished_ts: Optional[float] = None
    output_tokens: int = 0
    shared_prefix_tokens: int = 0
    # repr() of the failure for 'cancelled'/'aborted' terminals that
    # have one (deadline expiry, recovery abort); None on clean exits.
    error: Optional[str] = None

    # -- derived latencies --------------------------------------------
    def queue_seconds(self) -> Optional[float]:
        if self.admitted_ts is None:
            return None
        return self.admitted_ts - self.queued_ts

    def ttft_seconds(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.queued_ts

    def tpot_seconds(self) -> Optional[float]:
        """Mean seconds per output token after the first."""
        if (self.first_token_ts is None or self.finished_ts is None or
                self.output_tokens < 2):
            return None
        return ((self.finished_ts - self.first_token_ts) /
                (self.output_tokens - 1))

    def total_seconds(self) -> Optional[float]:
        if self.finished_ts is None:
            return None
        return self.finished_ts - self.queued_ts

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d['queue_seconds'] = self.queue_seconds()
        d['ttft_seconds'] = self.ttft_seconds()
        d['tpot_seconds'] = self.tpot_seconds()
        d['total_seconds'] = self.total_seconds()
        return d


class TraceStore:
    """In-flight traces + a bounded ring of completed ones."""

    def __init__(self, capacity: int = 256,
                 jsonl_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._inflight: Dict[int, RequestTrace] = {}
        self._completed: 'collections.deque[RequestTrace]' = (
            collections.deque(maxlen=max(1, capacity)))
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        self._jsonl_failed = False

    # -- lifecycle -----------------------------------------------------
    def begin(self, request_id: int, prompt_tokens: int = 0,
              http_request_id: Optional[str] = None) -> RequestTrace:
        now = time.time()
        trace = RequestTrace(request_id=request_id, queued_ts=now,
                             prompt_tokens=prompt_tokens,
                             http_request_id=http_request_id)
        with self._lock:
            self._inflight[request_id] = trace
        self._emit_event(now, request_id, 'queued',
                         prompt_tokens=prompt_tokens)
        return trace

    def annotate(self, request_id: int, **fields: Any) -> None:
        """Attach metadata (e.g. the HTTP request id) to a live trace."""
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is None:
                return
            for k, v in fields.items():
                if hasattr(trace, k):
                    setattr(trace, k, v)

    def event(self, request_id: int, name: str, **fields: Any) -> None:
        """Stamp a lifecycle event on a live trace.

        Known events: 'admitted', 'prefill_chunk', 'prefill_done',
        'first_token'.  Unknown request ids are ignored (the request
        may have been evicted between the caller's check and now).
        """
        now = time.time()
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is None:
                return
            if name == 'admitted':
                trace.admitted_ts = now
                trace.state = 'prefilling'
                trace.shared_prefix_tokens = fields.get(
                    'shared_prefix_tokens', 0)
            elif name == 'prefill_chunk':
                trace.prefill_chunks += 1
            elif name == 'prefill_done':
                trace.prefill_done_ts = now
                trace.state = 'decoding'
            elif name == 'first_token':
                trace.first_token_ts = now
        # prefill_chunk is per-chunk noise; keep the sink to transitions.
        if name != 'prefill_chunk':
            self._emit_event(now, request_id, name, **fields)

    def finish(self, request_id: int, state: str,
               output_tokens: Optional[int] = None,
               error: Optional[str] = None
               ) -> Optional[RequestTrace]:
        """Move a trace to a terminal state; idempotent per request."""
        assert state in TERMINAL_STATES, state
        now = time.time()
        with self._lock:
            trace = self._inflight.pop(request_id, None)
            if trace is None:
                return None
            trace.finished_ts = now
            trace.state = state
            if output_tokens is not None:
                trace.output_tokens = output_tokens
            if error is not None:
                trace.error = error
            self._completed.append(trace)
        self._emit_event(now, request_id, state,
                         output_tokens=trace.output_tokens)
        return trace

    def abort_all(self, state: str = 'aborted',
                  error: Optional[str] = None) -> List[RequestTrace]:
        """Terminate every in-flight trace (engine fatal / shutdown)."""
        now = time.time()
        with self._lock:
            traces = list(self._inflight.values())
            self._inflight.clear()
            for t in traces:
                t.finished_ts = now
                t.state = state
                if error is not None:
                    t.error = error
                self._completed.append(t)
        for t in traces:
            self._emit_event(now, t.request_id, state,
                             output_tokens=t.output_tokens)
        return traces

    # -- introspection -------------------------------------------------
    @property
    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def get(self, request_id: int) -> Optional[RequestTrace]:
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is not None:
                return trace
            for t in self._completed:
                if t.request_id == request_id:
                    return t
        return None

    def recent(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-first trace dicts: in-flight first, then completed."""
        with self._lock:
            live = sorted(self._inflight.values(),
                          key=lambda t: t.queued_ts, reverse=True)
            done = list(self._completed)[::-1]
        out = [t.to_dict() for t in live + done]
        return out[:max(0, limit)]

    # -- JSONL sink ----------------------------------------------------
    def _emit_event(self, ts: float, request_id: int, event: str,
                    **fields: Any) -> None:
        if self._jsonl_path is None or self._jsonl_failed:
            return
        rec = {'ts': ts, 'rid': request_id, 'event': event}
        rec.update(fields)
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                if self._jsonl_file is None:
                    self._jsonl_file = open(self._jsonl_path, 'a',
                                            buffering=1)
                self._jsonl_file.write(line + '\n')
            except OSError:
                # Telemetry must never take the engine down.
                self._jsonl_failed = True

    def close(self) -> None:
        with self._lock:
            if self._jsonl_file is not None:
                try:
                    self._jsonl_file.close()
                except OSError:
                    pass
                self._jsonl_file = None
