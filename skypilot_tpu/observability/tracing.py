"""Per-request lifecycle traces for the serving engines.

A `RequestTrace` records the host-side timeline of one request:

    queued -> admitted -> prefill chunk(s) -> first token -> decode
           -> finished | cancelled | evicted | aborted

and derives the latencies that matter for serving SLOs: queue wait,
TTFT (time to first token, measured from submit), and TPOT (mean
per-output-token latency over the decode phase).

The `TraceStore` keeps in-flight traces in a dict keyed by request id
plus a bounded ring of completed traces (newest last), and can mirror
every transition to a JSONL event sink for offline ingestion
(`SKYTPU_TRACE_JSONL=<path>`, read by the engines).  All methods are
thread-safe and O(1); nothing here touches JAX or device memory.

Distributed tracing rides on top: a `Span` is one timed operation in
one process, a `SpanStore` groups spans by trace id, and the trace id
IS the external `X-Request-Id` — the router opens a root span per
request, stamps `X-Skytpu-Trace: <trace_id>/<span_id>` on each
upstream attempt, and the replica annotates its engine trace with both
ids so `GET /traces?id=...&stitch=1` on the router can join the
router-side spans with every replica-side engine timeline into one
stitched document (including the failed attempts of a failover).
"""
from __future__ import annotations

import collections
import dataclasses
import json
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

# Terminal states a trace can land in.  'handed_off' is terminal for
# the PREFILL-role replica only: the request lives on, but on another
# replica's timeline (joined via the shared http_request_id).
# 'migrated' is its drain-time sibling: the VICTIM replica
# checkpointed the live slot and a survivor resumed it mid-generation
# (same http_request_id join).
TERMINAL_STATES = ('finished', 'cancelled', 'evicted', 'aborted',
                   'handed_off', 'migrated')

# Propagation header carrying `<trace_id>/<parent_span_id>` from the
# router to the replica it tries.  The trace id is the external
# X-Request-Id; the parent span id names the router's attempt span so
# a replica's work nests under the exact attempt that reached it.
# The name itself lives in the protocol contract (single source for
# every fleet wire header); this re-export is the historical spelling.
from skypilot_tpu.protocol import TRACE_HEADER

# Both halves share the router's request-id charset; anything else is
# treated as absent rather than trusted.
_CTX_RE = re.compile(r'^([A-Za-z0-9._:-]{1,64})/([A-Za-z0-9._:-]{1,64})$')


def format_trace_context(trace_id: str, span_id: str) -> str:
    """Render the `X-Skytpu-Trace` header value."""
    return f'{trace_id}/{span_id}'


def parse_trace_context(value: Optional[str]
                        ) -> Optional[Tuple[str, str]]:
    """`(trace_id, parent_span_id)` from a header value, or None if
    the value is missing or malformed (never raises: a bad header from
    an arbitrary client must not fail the request)."""
    if not value:
        return None
    m = _CTX_RE.match(value.strip())
    if m is None:
        return None
    return m.group(1), m.group(2)


@dataclasses.dataclass
class Span:
    """One timed operation inside one process, keyed to a trace id.

    Mutated only by the thread that started it (attrs/end); readers go
    through `SpanStore` snapshots which copy the fields under the
    store lock."""
    trace_id: str
    span_id: str
    name: str
    start_ts: float
    parent_id: Optional[str] = None
    end_ts: Optional[float] = None
    status: str = 'ok'
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def end(self, status: Optional[str] = None, **attrs: Any) -> None:
        """Close the span; idempotent (first end wins the timestamp)."""
        if self.end_ts is None:
            self.end_ts = time.time()
        if status is not None:
            self.status = status
        self.attrs.update(attrs)

    def duration_seconds(self) -> Optional[float]:
        if self.end_ts is None:
            return None
        return self.end_ts - self.start_ts

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d['duration_seconds'] = self.duration_seconds()
        return d


class SpanStore:
    """Spans grouped by trace id, bounded by distinct-trace count.

    The router holds one of these: a root span per proxied request
    plus one child span per upstream attempt.  Eviction is
    oldest-trace-first, so a scrape always sees whole traces (never a
    trace with its early spans dropped)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._traces: 'collections.OrderedDict[str, List[Span]]' = (
            collections.OrderedDict())
        self._capacity = max(1, capacity)

    @staticmethod
    def new_span_id() -> str:
        return uuid.uuid4().hex[:16]

    def start(self, trace_id: str, name: str,
              parent_id: Optional[str] = None, **attrs: Any) -> Span:
        span = Span(trace_id=trace_id, span_id=self.new_span_id(),
                    name=name, start_ts=time.time(),
                    parent_id=parent_id, attrs=dict(attrs))
        with self._lock:
            if trace_id not in self._traces:
                while len(self._traces) >= self._capacity:
                    self._traces.popitem(last=False)
                self._traces[trace_id] = []
            self._traces[trace_id].append(span)
        return span

    def get(self, trace_id: str) -> List[Dict[str, Any]]:
        """Span dicts for one trace, in start order ([] if unknown)."""
        with self._lock:
            spans = list(self._traces.get(trace_id, ()))
        return [s.to_dict() for s in spans]

    def recent(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first `{trace_id, spans}` documents."""
        with self._lock:
            items = [(tid, list(spans)) for tid, spans
                     in self._traces.items()][::-1]
        return [{'trace_id': tid,
                 'spans': [s.to_dict() for s in spans]}
                for tid, spans in items[:max(0, limit)]]

    @property
    def trace_count(self) -> int:
        with self._lock:
            return len(self._traces)


@dataclasses.dataclass
class RequestTrace:
    """Host-side timeline of one request (all timestamps time.time())."""
    request_id: int
    queued_ts: float
    prompt_tokens: int = 0
    http_request_id: Optional[str] = None
    # Parent span id from the router's X-Skytpu-Trace header, when the
    # request arrived through the fleet router (None for direct hits).
    trace_parent: Optional[str] = None
    state: str = 'queued'
    admitted_ts: Optional[float] = None
    prefill_chunks: int = 0
    prefill_done_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finished_ts: Optional[float] = None
    output_tokens: int = 0
    # Decode steps this request participated in.  NOT the same as
    # output_tokens: speculative decoding commits up to spec_k+1
    # tokens per step (and the seeded first token arrives with
    # prefill, taking no decode step at all), so tokens > steps on a
    # speculating engine.  Latency math must divide by TOKENS;
    # steps/token (the speculation win) is tokens_per_step()'s
    # inverse.
    decode_steps: int = 0
    # Engine step-ledger join: the global step indices of the FIRST
    # and LAST steps that committed a token for this request, so
    # /traces?id= timing lines up against GET /profile/steps records
    # ("this token waited on step 4812, a 96-token prefill-mix step").
    # None until the first commit / on engines without a step counter.
    first_step_idx: Optional[int] = None
    last_step_idx: Optional[int] = None
    shared_prefix_tokens: int = 0
    # repr() of the failure for 'cancelled'/'aborted' terminals that
    # have one (deadline expiry, recovery abort); None on clean exits.
    error: Optional[str] = None

    # -- derived latencies --------------------------------------------
    def queue_seconds(self) -> Optional[float]:
        if self.admitted_ts is None:
            return None
        return self.admitted_ts - self.queued_ts

    def ttft_seconds(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.queued_ts

    def tpot_seconds(self) -> Optional[float]:
        """Mean seconds per output token after the first — derived
        from TOKENS EMITTED, never from decode steps: a speculative
        step commits several tokens, so a per-step derivation would
        overstate TPOT by the acceptance factor (and the TPOT SLO
        verdict with it)."""
        if (self.first_token_ts is None or self.finished_ts is None or
                self.output_tokens < 2):
            return None
        return ((self.finished_ts - self.first_token_ts) /
                (self.output_tokens - 1))

    def tokens_per_step(self) -> Optional[float]:
        """Mean tokens committed per decode step (> 1 when
        speculation is accepting; None before any step)."""
        if self.decode_steps <= 0:
            return None
        return self.output_tokens / self.decode_steps

    def total_seconds(self) -> Optional[float]:
        if self.finished_ts is None:
            return None
        return self.finished_ts - self.queued_ts

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d['queue_seconds'] = self.queue_seconds()
        d['ttft_seconds'] = self.ttft_seconds()
        d['tpot_seconds'] = self.tpot_seconds()
        d['tokens_per_step'] = self.tokens_per_step()
        d['total_seconds'] = self.total_seconds()
        return d


class TraceStore:
    """In-flight traces + a bounded ring of completed ones."""

    def __init__(self, capacity: int = 256,
                 jsonl_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._inflight: Dict[int, RequestTrace] = {}
        self._completed: 'collections.deque[RequestTrace]' = (
            collections.deque(maxlen=max(1, capacity)))
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        self._jsonl_failed = False

    # -- lifecycle -----------------------------------------------------
    def begin(self, request_id: int, prompt_tokens: int = 0,
              http_request_id: Optional[str] = None) -> RequestTrace:
        now = time.time()
        trace = RequestTrace(request_id=request_id, queued_ts=now,
                             prompt_tokens=prompt_tokens,
                             http_request_id=http_request_id)
        with self._lock:
            self._inflight[request_id] = trace
        self._emit_event(now, request_id, 'queued',
                         http_request_id=http_request_id,
                         prompt_tokens=prompt_tokens)
        return trace

    def annotate(self, request_id: int, **fields: Any) -> None:
        """Attach metadata (e.g. the HTTP request id) to a live trace."""
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is None:
                return
            for k, v in fields.items():
                if hasattr(trace, k):
                    setattr(trace, k, v)

    def event(self, request_id: int, name: str, **fields: Any) -> None:
        """Stamp a lifecycle event on a live trace.

        Known events: 'admitted', 'prefill_chunk', 'prefill_done',
        'first_token'.  Unknown request ids are ignored (the request
        may have been evicted between the caller's check and now).
        """
        now = time.time()
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is None:
                return
            if name == 'admitted':
                trace.admitted_ts = now
                trace.state = 'prefilling'
                trace.shared_prefix_tokens = fields.get(
                    'shared_prefix_tokens', 0)
            elif name == 'prefill_chunk':
                trace.prefill_chunks += 1
            elif name == 'prefill_done':
                trace.prefill_done_ts = now
                trace.state = 'decoding'
            elif name == 'first_token':
                trace.first_token_ts = now
            http_id = trace.http_request_id
        # prefill_chunk is per-chunk noise; keep the sink to transitions.
        if name != 'prefill_chunk':
            self._emit_event(now, request_id, name,
                             http_request_id=http_id, **fields)

    def finish(self, request_id: int, state: str,
               output_tokens: Optional[int] = None,
               error: Optional[str] = None,
               decode_steps: Optional[int] = None,
               first_step_idx: Optional[int] = None,
               last_step_idx: Optional[int] = None
               ) -> Optional[RequestTrace]:
        """Move a trace to a terminal state; idempotent per request."""
        assert state in TERMINAL_STATES, state
        now = time.time()
        with self._lock:
            trace = self._inflight.pop(request_id, None)
            if trace is None:
                return None
            trace.finished_ts = now
            trace.state = state
            if output_tokens is not None:
                trace.output_tokens = output_tokens
            if decode_steps is not None:
                trace.decode_steps = decode_steps
            if first_step_idx is not None:
                trace.first_step_idx = first_step_idx
            if last_step_idx is not None:
                trace.last_step_idx = last_step_idx
            if error is not None:
                trace.error = error
            self._completed.append(trace)
        self._emit_event(now, request_id, state,
                         http_request_id=trace.http_request_id,
                         output_tokens=trace.output_tokens)
        return trace

    def abort_all(self, state: str = 'aborted',
                  error: Optional[str] = None) -> List[RequestTrace]:
        """Terminate every in-flight trace (engine fatal / shutdown)."""
        now = time.time()
        with self._lock:
            traces = list(self._inflight.values())
            self._inflight.clear()
            for t in traces:
                t.finished_ts = now
                t.state = state
                if error is not None:
                    t.error = error
                self._completed.append(t)
        for t in traces:
            self._emit_event(now, t.request_id, state,
                             http_request_id=t.http_request_id,
                             output_tokens=t.output_tokens)
        return traces

    # -- introspection -------------------------------------------------
    @property
    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def get(self, request_id: int) -> Optional[RequestTrace]:
        with self._lock:
            trace = self._inflight.get(request_id)
            if trace is not None:
                return trace
            for t in self._completed:
                if t.request_id == request_id:
                    return t
        return None

    def recent(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-first trace dicts: in-flight first, then completed."""
        with self._lock:
            live = sorted(self._inflight.values(),
                          key=lambda t: t.queued_ts, reverse=True)
            done = list(self._completed)[::-1]
        out = [t.to_dict() for t in live + done]
        return out[:max(0, limit)]

    # -- JSONL sink ----------------------------------------------------
    def _emit_event(self, ts: float, request_id: int, event: str,
                    **fields: Any) -> None:
        if self._jsonl_path is None or self._jsonl_failed:
            return
        rec = {'ts': ts, 'rid': request_id, 'event': event}
        # Drop absent annotations (e.g. http_request_id on a direct
        # engine use) so offline joins key on presence, not null.
        rec.update({k: v for k, v in fields.items() if v is not None})
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                if self._jsonl_file is None:
                    self._jsonl_file = open(self._jsonl_path, 'a',
                                            buffering=1)
                self._jsonl_file.write(line + '\n')
            except OSError:
                # Telemetry must never take the engine down.
                self._jsonl_failed = True

    def close(self) -> None:
        with self._lock:
            if self._jsonl_file is not None:
                try:
                    self._jsonl_file.close()
                except OSError:
                    pass
                self._jsonl_file = None
