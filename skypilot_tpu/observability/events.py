"""Flight recorder: a bounded ring of structured operational events.

Where `tracing.TraceStore` follows ONE request through its lifecycle,
the `EventRing` records the fleet-level control-plane story around all
of them — supervisor restarts, breaker transitions, drains, scale
decisions, chaos injections — so that after an incident the operator
can read back "what did the system decide, and when" without grepping
logs.  Both router and replica expose their ring at `GET /events`.

`EVENT_CONTRACT` is the single source of truth for event names, the
exact analogue of `METRIC_CONTRACT` for metric names: the skylint
`trace-discipline` rule requires every `TraceStore.event(...)` and
`EventRing.record(...)` call site to pass a string literal drawn from
this set, so the taxonomy below is exhaustive by construction.

Pure stdlib; safe to import from any layer.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List

# The event taxonomy.  Two families share one namespace so a single
# skylint rule covers both call surfaces:
#
# - request-lifecycle events, stamped on a RequestTrace via
#   `TraceStore.event(rid, name)` (the store itself emits the
#   'queued' + terminal transitions internally);
# - fleet/control-plane events, recorded on an EventRing via
#   `EventRing.record(name, **fields)`.
EVENT_CONTRACT = frozenset({
    # -- request lifecycle (TraceStore.event) -------------------------
    'admitted',
    'prefill_chunk',
    'prefill_done',
    'first_token',
    'handoff_export',         # prefill-role replica serialized the KV
    'handoff_admitted',       # decode-role replica admitted mid-stream
    'migrate_export',         # draining replica checkpointed a live slot
    'migrate_resume',         # survivor resumed a migrated slot
    # -- router data plane (EventRing.record) -------------------------
    'breaker_transition',     # CircuitBreaker state change
    'replica_unhealthy',      # health probe flipped a replica down
    # -- replica server -----------------------------------------------
    'decode_loop_restart',    # supervised decode loop recovered
    'stall_detected',         # watchdog saw a wedged step
    'replica_failed',         # restart budget exhausted / fatal error
    'drain_begin',            # replica stopped admitting (scale-down)
    'drain_complete',         # drain finished; replica exiting
    'device_profile_armed',   # POST /profile/device accepted a window
    'device_profile_started',  # first busy step opened the capture
    'device_profile_done',    # windowed jax.profiler capture finished
    'device_profile_failed',  # capture could not start/stop; disarmed
    # -- replica supervisor -------------------------------------------
    'replica_spawn',          # new replica process launched
    'replica_restart',        # crash scheduled for backoff + respawn
    'scale_up',               # autoscaler grew the fleet
    'scale_down',             # autoscaler shrank the fleet
    # -- fault injection ----------------------------------------------
    'chaos_injection',        # a chaos fault point fired
})


class EventRing:
    """Thread-safe bounded ring of `{ts, seq, event, ...fields}` dicts.

    `record()` validates the name against `EVENT_CONTRACT` (a typo'd
    event name is a programming error, not data) and optionally counts
    into `skytpu_events_total{kind=...}` when built with a registry.
    `snapshot()` returns newest-first copies; the ring itself never
    grows past `capacity`, so a wedged scraper cannot OOM the server.
    """

    def __init__(self, capacity: int = 512, registry: Any = None,
                 source: str = ''):
        self._lock = threading.Lock()
        self._ring: 'collections.deque[Dict[str, Any]]' = (
            collections.deque(maxlen=max(1, capacity)))
        self._seq = 0
        self._source = source
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                'skytpu_events_total',
                'Flight-recorder events recorded, by kind.',
                labelnames=('kind',))

    def record(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Append one event; returns the stored record (a copy is NOT
        made — callers must not mutate it afterwards)."""
        if event not in EVENT_CONTRACT:
            raise ValueError(
                f'unknown event name {event!r}: add it to '
                f'observability.events.EVENT_CONTRACT in the same '
                f'change that records it')
        rec: Dict[str, Any] = {'ts': time.time(), 'event': event}
        if self._source:
            rec['source'] = self._source
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec['seq'] = self._seq
            self._ring.append(rec)
        if self._counter is not None:
            self._counter.labels(kind=event).inc()
        return rec

    def snapshot(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-first copies of the most recent `limit` events."""
        with self._lock:
            out = [dict(r) for r in list(self._ring)[::-1]]
        return out[:max(0, limit)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_recorded(self) -> int:
        """Monotonic count of every record() ever made (ring may have
        evicted older ones)."""
        with self._lock:
            return self._seq


