"""Step-level performance ledger: a bounded per-step flight recorder.

The histogram aggregates (`skytpu_step_*_seconds`) say a replica is
slow; they cannot say WHICH step, what was in it, or whether it was
compute- or memory-bound.  `StepLedger` answers those questions: a
bounded ring of per-step records fed by the serving engines at
step-COMMIT time — always the scheduler thread, always the consume
half of the dispatch/consume split, never the dispatch half — with
data already in hand there (timestamps, batch composition, the
precomputed KV read-byte totals, page-pool state).

Each record is stamped with an analytic roofline verdict at append
time: the engine passes the model's FLOP constants (from
``models.flops_per_token_parts``) and the chip's peak/bandwidth (from
``utils/accelerator_registry``), and ``record()`` derives achieved
MFU plus an arithmetic-intensity verdict (``memory_bound`` when the
step's FLOPs/byte sits below the machine-balance ridge,
``compute_bound`` above it).

Disabled mode mirrors ``metrics.Registry``: ``record()`` returns
before computing or locking anything, so a ledger-off engine pays one
attribute read and a branch per step — the bench's ledger-off rerun
asserts bit-identical greedy streams and the <2% publish-overhead
contract covers the enabled path.

Lock discipline: the ring deque is mutated ONLY under ``self._lock``
(skylint lock-discipline covers this file); records themselves are
immutable-after-append dicts, so snapshots hand out the dicts without
copying.  Nothing here imports JAX — the module stays importable from
any layer, like the rest of observability/.
"""
from __future__ import annotations

import collections
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence

MEMORY_BOUND = 'memory_bound'
COMPUTE_BOUND = 'compute_bound'


class StepLedger:
    """Bounded ring of per-step performance records.

    `flops_per_token_base` is the context-free forward cost of one
    token (2·active-params); `attn_flops_per_ctx_token` the extra
    FLOPs per (token, live-context-position) pair — together they
    price a step as ``tokens * base + attn * ctx_sum`` where
    ``ctx_sum`` sums each committed token's live context length.
    `peak_flops_per_sec` / `hbm_bytes_per_sec` are whole-engine
    (per-chip figures times chip count); their ratio is the roofline
    ridge in FLOPs/byte.
    """

    def __init__(self, capacity: int = 512, enabled: bool = True, *,
                 flops_per_token_base: float = 0.0,
                 attn_flops_per_ctx_token: float = 0.0,
                 peak_flops_per_sec: float = 0.0,
                 hbm_bytes_per_sec: float = 0.0,
                 model: str = '', device_kind: str = '',
                 n_chips: int = 1):
        self._lock = threading.Lock()
        self.capacity = max(1, int(capacity))
        self._ring: 'collections.deque[Dict[str, Any]]' = (
            collections.deque(maxlen=self.capacity))
        self.enabled = bool(enabled)
        self.flops_per_token_base = float(flops_per_token_base)
        self.attn_flops_per_ctx_token = float(attn_flops_per_ctx_token)
        self.peak_flops_per_sec = float(peak_flops_per_sec)
        self.hbm_bytes_per_sec = float(hbm_bytes_per_sec)
        # Machine balance: FLOPs the chip can afford per HBM byte
        # moved.  A step whose arithmetic intensity sits below this
        # ridge cannot reach peak — it is waiting on the memory
        # system, not the MXU.
        self.ridge_flops_per_byte = (
            self.peak_flops_per_sec / self.hbm_bytes_per_sec
            if self.peak_flops_per_sec > 0 and self.hbm_bytes_per_sec > 0
            else 0.0)
        self.model = model
        self.device_kind = device_kind
        self.n_chips = max(1, int(n_chips))
        self._recorded = 0          # lifetime count (ring evicts)

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    # -- feed (scheduler thread, consume half only) -------------------
    def record(self, *, step: int, mode: str,
               t_enter: float, t_dispatch: float,
               t_join: Optional[float], t_commit: float,
               rows: int, tokens: int, ctx_sum: int,
               read_bytes: float = 0.0,
               mix_tokens: int = 0,
               spec_proposed: int = 0, spec_accepted: int = 0,
               decode_kernel: str = '', prefill_kernel: str = '',
               free_pages: Optional[int] = None,
               used_pages: Optional[int] = None,
               compiled: bool = False) -> Optional[Dict[str, Any]]:
        """Append one step-commit record; returns it (None when
        disabled).  Everything passed in is host-side scalars the
        scheduler thread already holds — no device reads, ever."""
        if not self.enabled:
            return None
        flops = (tokens * self.flops_per_token_base
                 + self.attn_flops_per_ctx_token * ctx_sum)
        step_s = max(t_commit - t_dispatch, 1e-9)
        mfu = (flops / (step_s * self.peak_flops_per_sec)
               if self.peak_flops_per_sec > 0 else 0.0)
        ai = flops / read_bytes if read_bytes > 0 else 0.0
        if self.ridge_flops_per_byte > 0:
            verdict = (MEMORY_BOUND if ai < self.ridge_flops_per_byte
                       else COMPUTE_BOUND)
        else:
            verdict = MEMORY_BOUND if read_bytes > 0 else COMPUTE_BOUND
        rec: Dict[str, Any] = {
            'step': step,
            'mode': mode,
            't_enter': t_enter,
            't_dispatch': t_dispatch,
            't_join': t_join,
            't_commit': t_commit,
            'dispatch_s': t_dispatch - t_enter,
            'step_s': step_s,
            'rows': rows,
            'tokens': tokens,
            'ctx_sum': ctx_sum,
            'mix_tokens': mix_tokens,
            'spec_proposed': spec_proposed,
            'spec_accepted': spec_accepted,
            'read_bytes': read_bytes,
            'decode_kernel': decode_kernel,
            'prefill_kernel': prefill_kernel,
            'free_pages': free_pages,
            'used_pages': used_pages,
            'compiled': compiled,
            'flops': flops,
            'flops_per_token': flops / tokens if tokens else 0.0,
            'mfu': mfu,
            'arith_intensity': ai,
            'roofline': verdict,
        }
        with self._lock:
            self._ring.append(rec)
            self._recorded += 1
        return rec

    # -- read side (any thread) ---------------------------------------
    def snapshot(self, limit: Optional[int] = None
                 ) -> List[Dict[str, Any]]:
        """Newest-last records; records are append-frozen, so the
        dicts are shared, not copied."""
        with self._lock:
            steps = list(self._ring)
        if limit is not None and limit >= 0:
            steps = steps[-limit:]
        return steps

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def info(self) -> Dict[str, Any]:
        """Config + state block for /health?verbose=1."""
        with self._lock:
            recorded = self._recorded
            held = len(self._ring)
            last = self._ring[-1] if self._ring else None
        out: Dict[str, Any] = {
            'enabled': self.enabled,
            'capacity': self.capacity,
            'recorded': recorded,
            'held': held,
            'model': self.model,
            'device_kind': self.device_kind,
            'n_chips': self.n_chips,
            'peak_tflops': self.peak_flops_per_sec / 1e12,
            'hbm_gbps': self.hbm_bytes_per_sec / 1e9,
            'ridge_flops_per_byte': self.ridge_flops_per_byte,
            'flops_per_token_base': self.flops_per_token_base,
            'attn_flops_per_ctx_token': self.attn_flops_per_ctx_token,
        }
        if last is not None:
            out['last_step'] = last['step']
            out['last_mfu'] = last['mfu']
            out['last_roofline'] = last['roofline']
        return out

    def summary(self) -> Dict[str, Any]:
        """Window aggregate over the held ring: achieved MFU, step-time
        percentiles, roofline mix — the bench `ledger` block and the
        router's /fleet/profile aggregation both consume this shape."""
        return summarize_steps(self.snapshot())


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def summarize_steps(steps: Sequence[Dict[str, Any]]
                    ) -> Dict[str, Any]:
    """Aggregate a list of ledger records (local ring or a replica's
    /profile/steps payload) into the shared summary shape."""
    n = len(steps)
    if n == 0:
        return {'steps': 0, 'achieved_mfu': 0.0, 'mfu_last': 0.0,
                'step_ms_p50': 0.0, 'step_ms_p99': 0.0,
                'tokens_per_sec': 0.0, 'flops_per_token': 0.0,
                'roofline': {MEMORY_BOUND: 0.0, COMPUTE_BOUND: 0.0},
                'roofline_verdict': None}
    durs = sorted(float(s['step_s']) for s in steps)
    mem = sum(1 for s in steps if s['roofline'] == MEMORY_BOUND)
    tokens = sum(int(s['tokens']) for s in steps)
    window_s = max(float(steps[-1]['t_commit'])
                   - float(steps[0]['t_dispatch']), 1e-9)
    mem_frac = mem / n
    return {
        'steps': n,
        'achieved_mfu': sum(float(s['mfu']) for s in steps) / n,
        'mfu_last': float(steps[-1]['mfu']),
        'step_ms_p50': _percentile(durs, 0.5) * 1e3,
        'step_ms_p99': _percentile(durs, 0.99) * 1e3,
        'tokens_per_sec': tokens / window_s,
        'flops_per_token': (sum(float(s['flops_per_token'])
                                for s in steps) / n),
        'roofline': {MEMORY_BOUND: mem_frac,
                     COMPUTE_BOUND: 1.0 - mem_frac},
        'roofline_verdict': (MEMORY_BOUND if mem_frac >= 0.5
                             else COMPUTE_BOUND),
    }


# -- unified Perfetto timeline ---------------------------------------
def chrome_trace(steps: Iterable[Dict[str, Any]],
                 traces: Iterable[Dict[str, Any]] = (),
                 pid: Optional[int] = None,
                 process_name: str = 'skytpu-replica'
                 ) -> Dict[str, Any]:
    """One Chrome-trace-event document (the utils/timeline.py schema:
    ``{'traceEvents': [...], 'displayTimeUnit': 'ms'}``) joining the
    ledger's engine-step slices with RequestTrace lifecycle rows so
    control plane and data plane open in a single Perfetto view.

    Ledger timestamps are perf-counter seconds; RequestTrace
    timestamps are wall-clock seconds — both map onto the SAME
    monotonic-anchored epoch via utils/timeline's offset helpers, so
    an NTP step mid-serve cannot make rows disagree.  Steps ride
    tid 0; each request gets its own tid (named via 'M' metadata
    events) with queued/prefill/decode phase slices whose args carry
    the first/last ledger step indices — the /traces?id= join.
    """
    from skypilot_tpu.utils import timeline as timeline_lib
    if pid is None:
        pid = os.getpid()
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = [
        {'name': 'process_name', 'ph': 'M', 'pid': pid, 'ts': 0,
         'args': {'name': process_name}},
        {'name': 'thread_name', 'ph': 'M', 'pid': pid, 'tid': 0,
         'ts': 0, 'args': {'name': 'engine steps'}},
    ]
    for rec in steps:
        ts = timeline_lib.perf_counter_to_epoch_us(rec['t_dispatch'])
        dur = max(1, int(float(rec['step_s']) * 1e6))
        events.append({
            'name': f"step {rec['step']} [{rec['mode']}]",
            'cat': 'engine_step', 'ph': 'X',
            'ts': ts, 'dur': dur, 'pid': pid, 'tid': 0,
            'args': {
                'step': rec['step'], 'mode': rec['mode'],
                'rows': rec['rows'], 'tokens': rec['tokens'],
                'mix_tokens': rec['mix_tokens'],
                'spec_proposed': rec['spec_proposed'],
                'spec_accepted': rec['spec_accepted'],
                'read_bytes': rec['read_bytes'],
                'decode_kernel': rec['decode_kernel'],
                'prefill_kernel': rec['prefill_kernel'],
                'free_pages': rec['free_pages'],
                'mfu': rec['mfu'],
                'roofline': rec['roofline'],
                'arith_intensity': rec['arith_intensity'],
                'compiled': rec['compiled'],
            }})
    tid = 0
    for tr in traces:
        tid += 1
        rid = tr.get('request_id')
        meta.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                     'tid': tid, 'ts': 0,
                     'args': {'name': f'req {rid}'}})
        join_args = {
            'request_id': rid,
            'http_request_id': tr.get('http_request_id'),
            'state': tr.get('state'),
            'first_step_idx': tr.get('first_step_idx'),
            'last_step_idx': tr.get('last_step_idx'),
            'output_tokens': tr.get('output_tokens'),
            'decode_steps': tr.get('decode_steps'),
        }
        q = tr.get('queued_ts')
        adm = tr.get('admitted_ts')
        pre = tr.get('prefill_done_ts')
        fin = tr.get('finished_ts')
        now_us = timeline_lib.now_epoch_us()

        def _us(wall_s: Optional[float]) -> Optional[int]:
            return None if wall_s is None else int(wall_s * 1e6)

        phases = (('queued', _us(q), _us(adm)),
                  ('prefill', _us(adm), _us(pre)),
                  ('decode', _us(pre), _us(fin)))
        for phase, start, end in phases:
            if start is None:
                continue
            if end is None:
                end = now_us      # still-live phase: open to "now"
            if end < start:
                end = start
            events.append({
                'name': f'{phase} req {rid}', 'cat': 'request',
                'ph': 'X', 'ts': start, 'dur': max(1, end - start),
                'pid': pid, 'tid': tid, 'args': join_args})
    events.sort(key=lambda e: e['ts'])
    return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}
