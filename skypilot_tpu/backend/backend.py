"""Backend ABC + cluster handle.

Counterpart of the reference's sky/backends/backend.py:24-197 (ResourceHandle
+ Backend with timeline/usage instrumentation on every API) and the handle
part of CloudVmRayResourceHandle (cloud_vm_ray_backend.py:2156): the handle
is the pickled-into-SQLite record of everything needed to reach a cluster
later — provider config, cached host addresses, launched resources.

TPU twist: `num_hosts_per_node` is structural (from the slice spec), and
host addresses are a flat rank-ordered list (head slice's hosts first),
which is exactly the order the gang driver assigns ranks in.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import resources as resources_lib
from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.provision import common as provision_common


class ClusterHandle:
    """Serializable record of a provisioned cluster."""

    _VERSION = 1

    def __init__(
        self,
        *,
        cluster_name: str,
        cluster_name_on_cloud: str,
        provider_name: str,
        provider_config: Dict[str, Any],
        launched_nodes: int,
        launched_resources: resources_lib.Resources,
        host_addresses: List[str],
        internal_ips: List[str],
        ssh_user: Optional[str] = None,
        ssh_key: Optional[str] = None,
    ) -> None:
        self.cluster_name = cluster_name
        self.cluster_name_on_cloud = cluster_name_on_cloud
        self.provider_name = provider_name
        self.provider_config = provider_config
        self.launched_nodes = launched_nodes
        self.launched_resources = launched_resources
        self.host_addresses = host_addresses
        self.internal_ips = internal_ips
        self.ssh_user = ssh_user
        self.ssh_key = ssh_key

    @property
    def num_hosts_per_node(self) -> int:
        """Reference num_ips_per_node (cloud_vm_ray_backend.py:2550)."""
        return self.launched_resources.num_hosts_per_node

    @property
    def num_hosts(self) -> int:
        return self.launched_nodes * self.num_hosts_per_node

    @property
    def head_address(self) -> str:
        return self.host_addresses[0]

    @property
    def head_internal_ip(self) -> str:
        return self.internal_ips[0]

    @property
    def head_agent_root(self) -> Optional[str]:
        """Explicit agent root for local hosts; None = remote $HOME."""
        if self.head_address.startswith('local:'):
            return self.head_address[len('local:'):]
        return None

    def __setstate__(self, state: Dict[str, Any]) -> None:
        """Version-aware unpickle: handles written by older clients get
        defaults for fields added since (reference:
        CloudVmRayResourceHandle.__setstate__ version upgrades)."""
        version = state.pop('_handle_version', 0)
        state.setdefault('ssh_user', None)
        state.setdefault('ssh_key', None)
        del version  # no field renames yet; bump _VERSION when needed
        self.__dict__.update(state)

    def __getstate__(self) -> Dict[str, Any]:
        state = dict(self.__dict__)
        state['_handle_version'] = self._VERSION
        return state

    def update_from_cluster_info(
            self, cluster_info: 'provision_common.ClusterInfo') -> None:
        tuples = cluster_info.ip_tuples()
        self.internal_ips = [t[0] for t in tuples]
        self.host_addresses = cluster_info.get_feasible_ips()
        if cluster_info.ssh_user is not None:
            self.ssh_user = cluster_info.ssh_user

    def __repr__(self) -> str:
        return (f'ClusterHandle(name={self.cluster_name!r}, '
                f'provider={self.provider_name}, '
                f'nodes={self.launched_nodes}, '
                f'hosts={len(self.host_addresses)}, '
                f'resources={self.launched_resources})')


class Backend:
    """Lifecycle operations on clusters (reference backend.py:30)."""

    NAME = 'backend'

    # -- provisioning ------------------------------------------------------
    @timeline.event
    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional[resources_lib.Resources],
                  dryrun: bool,
                  stream_logs: bool,
                  cluster_name: str,
                  retry_until_up: bool = False) -> Optional[ClusterHandle]:
        return self._provision(task, to_provision, dryrun, stream_logs,
                               cluster_name, retry_until_up)

    @timeline.event
    def sync_workdir(self, handle: ClusterHandle, workdir: str) -> None:
        return self._sync_workdir(handle, workdir)

    @timeline.event
    def sync_file_mounts(self, handle: ClusterHandle,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        return self._sync_file_mounts(handle, all_file_mounts,
                                      storage_mounts)

    @timeline.event
    def setup(self, handle: ClusterHandle, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        return self._setup(handle, task, detach_setup)

    @timeline.event
    def execute(self, handle: ClusterHandle, task: 'task_lib.Task',
                detach_run: bool, dryrun: bool = False) -> Optional[int]:
        return self._execute(handle, task, detach_run, dryrun)

    @timeline.event
    def teardown(self, handle: ClusterHandle, terminate: bool,
                 purge: bool = False) -> None:
        return self._teardown(handle, terminate, purge)

    # -- to be implemented -------------------------------------------------
    def _provision(self, task, to_provision, dryrun, stream_logs,
                   cluster_name, retry_until_up):
        raise NotImplementedError

    def _sync_workdir(self, handle, workdir):
        raise NotImplementedError

    def _sync_file_mounts(self, handle, all_file_mounts, storage_mounts):
        raise NotImplementedError

    def _setup(self, handle, task, detach_setup):
        raise NotImplementedError

    def _execute(self, handle, task, detach_run, dryrun):
        raise NotImplementedError

    def _teardown(self, handle, terminate, purge):
        raise NotImplementedError
