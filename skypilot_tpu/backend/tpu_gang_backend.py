"""TpuGangBackend: the concrete cluster runtime.

Counterpart of the reference's CloudVmRayBackend
(sky/backends/cloud_vm_ray_backend.py:2620-5115), restructured around TPU
slices and with Ray removed:

  - provisioning goes through provision/provisioner.RetryingProvisioner
    (zone→region→cloud failover with re-optimize, :1979/:2093-2150);
  - runtime setup replaces "install Ray + start head/workers"
    (instance_setup.py:250-331) with: ship the framework runtime, write the
    agent config, start the agent daemon on the head host;
  - job execution replaces RayCodeGen + `ray job submit` (:220-709, :3358)
    with an agent-RPC job submission and the gang job driver
    (agent/job_driver.py) fanning out one process per slice host;
  - `exec` fast path = SYNC_WORKDIR + EXEC only (execution.py:553).
"""
from __future__ import annotations

import getpass
import json
import os
import shlex
import subprocess
import sys
import time
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import rpc as agent_rpc
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.backend import command_runner as runner_lib
from skypilot_tpu.clouds import cloud as clouds_lib
from skypilot_tpu.provision import api as provision_api
from skypilot_tpu.provision import provisioner as provisioner_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

_RUNTIME_DIR = '.skytpu_runtime'
_SSH_RUNTIME_PREFIX = (
    f'export PYTHONPATH=$HOME/{_RUNTIME_DIR}:$PYTHONPATH; ')


class TpuGangBackend(backend_lib.Backend):

    NAME = 'tpu_gang'

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _runner_for(self, handle: backend_lib.ClusterHandle,
                    address: str) -> runner_lib.CommandRunner:
        return runner_lib.CommandRunner.from_address(
            address, ssh_user=handle.ssh_user, ssh_key=handle.ssh_key)

    def _head_runner(self, handle: backend_lib.ClusterHandle
                     ) -> runner_lib.CommandRunner:
        return self._runner_for(handle, handle.head_address)

    def _is_local(self, handle: backend_lib.ClusterHandle) -> bool:
        return handle.head_address.startswith('local:')

    def _runtime_prefix(self, handle: backend_lib.ClusterHandle) -> str:
        return '' if self._is_local(handle) else _SSH_RUNTIME_PREFIX

    def run_on_head(self, handle: backend_lib.ClusterHandle, cmd: str,
                    **kwargs: Any):
        """Reference run_on_head (cloud_vm_ray_backend.py:4485)."""
        return self._head_runner(handle).run(
            self._runtime_prefix(handle) + cmd, **kwargs)

    def _rpc(self, handle: backend_lib.ClusterHandle, method: str,
             **params: Any) -> Dict[str, Any]:
        """Execute an agent RPC on the head host (the reference's
        codegen-over-SSH channel, job_lib.py:930)."""
        root = handle.head_agent_root
        if root is not None:
            params['agent_root'] = root
            # Local clusters share our filesystem: dispatch in-process and
            # skip the ~2s interpreter spawn per call.
            result = agent_rpc.handle_request(method, params)
            if 'error' in result:
                raise exceptions.SkyTpuError(
                    f'Agent RPC {method} failed: {result["error"]}')
            return result['result']
        cmd = agent_rpc.make_rpc_command(method, **params)
        rc, stdout, stderr = self.run_on_head(handle, cmd,
                                              require_outputs=True,
                                              timeout=120)
        if rc != 0:
            raise exceptions.CommandError(rc, f'agent rpc {method}',
                                          stderr or stdout)
        response = agent_rpc.parse_response(stdout)
        if 'error' in response:
            raise exceptions.SkyTpuError(
                f'Agent RPC {method} failed: {response["error"]}')
        return response['result']

    # ------------------------------------------------------------------
    # provision
    # ------------------------------------------------------------------
    def _provision(self, task: 'task_lib.Task',
                   to_provision: Optional[resources_lib.Resources],
                   dryrun: bool, stream_logs: bool, cluster_name: str,
                   retry_until_up: bool = False
                   ) -> Optional[backend_lib.ClusterHandle]:
        del stream_logs
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is not None and record['status'] == \
                global_user_state.ClusterStatus.UP:
            handle: backend_lib.ClusterHandle = record['handle']
            self.check_resources_fit_cluster(handle, task)
            logger.info(f'Cluster {cluster_name!r} is UP; reusing.')
            # Refresh the shipped runtime and restart the agent if its
            # version is stale (reference: wheel re-ship on every launch
            # + attempt_skylet version gating).  rsync makes the ship
            # incremental; `exec` skips this via its fast path.
            self._post_provision_runtime_setup(handle)
            return handle
        resume = record is not None and record['status'] == \
            global_user_state.ClusterStatus.STOPPED
        if resume:
            # Resume must target where the stopped instances actually are,
            # not wherever the optimizer would place a fresh launch.
            old_handle: backend_lib.ClusterHandle = record['handle']
            self.check_resources_fit_cluster(old_handle, task)
            to_provision = old_handle.launched_resources
        elif to_provision is None:
            assert task.best_resources is not None, (
                'Run the optimizer before provisioning.')
            to_provision = task.best_resources
        if dryrun:
            logger.info(f'Dryrun: would provision {to_provision} '
                        f'x{task.num_nodes} as {cluster_name!r}.')
            return None

        max_len = (to_provision.cloud.MAX_CLUSTER_NAME_LEN_LIMIT
                   if to_provision.cloud else None) or 35
        cluster_name_on_cloud = common_utils.make_cluster_name_on_cloud(
            cluster_name, max_len)
        retrier = provisioner_lib.RetryingProvisioner(
            cluster_name, cluster_name_on_cloud,
            authentication_config=self._authentication_config(
                to_provision.cloud))

        backoff = common_utils.Backoff(initial_backoff=30)
        while True:
            try:
                if resume:
                    cloud = to_provision.cloud
                    region = typing.cast(Any, cloud).regions_with_offering(
                        None, None, False, to_provision.region,
                        to_provision.zone)[0]
                    # Resume pins the recorded zone (stopped instances
                    # only exist there).
                    zones = ([clouds_lib.Zone(name=to_provision.zone,
                                              region=region.name)]
                             if to_provision.zone else None)
                    result = provisioner_lib.bulk_provision(
                        cloud, region, zones,
                        cluster_name_on_cloud, task.num_nodes, to_provision,
                        authentication_config=self._authentication_config(
                            cloud),
                        resume_stopped_nodes=True)
                else:
                    result = retrier.provision_with_retries(
                        task, to_provision, task.num_nodes)
                break
            except exceptions.ResourcesUnavailableError as e:
                if not retry_until_up:
                    raise
                wait = backoff.current_backoff()
                logger.info(f'Retrying in {wait:.0f}s (retry_until_up): {e}')
                time.sleep(wait)

        handle = backend_lib.ClusterHandle(
            cluster_name=cluster_name,
            cluster_name_on_cloud=cluster_name_on_cloud,
            provider_name=result.provider_name,
            provider_config=result.provider_config,
            launched_nodes=task.num_nodes,
            launched_resources=result.resources,
            host_addresses=result.cluster_info.get_feasible_ips(),
            internal_ips=[t[0] for t in result.cluster_info.ip_tuples()],
            ssh_user=result.cluster_info.ssh_user,
            ssh_key=self._ssh_key_path(result.resources.cloud),
        )
        global_user_state.add_or_update_cluster(
            cluster_name, handle, set(task.resources), ready=False)
        self._post_provision_runtime_setup(handle)
        global_user_state.add_or_update_cluster(
            cluster_name, handle, set(task.resources), ready=True)
        owner = (result.resources.cloud.get_user_identities() or [None])[0] \
            if result.resources.cloud else None
        global_user_state.set_owner_identity_for_cluster(cluster_name, owner)
        return handle

    def check_resources_fit_cluster(self, handle: backend_lib.ClusterHandle,
                                    task: 'task_lib.Task') -> None:
        """Reference: Resources.less_demanding_than on exec/relaunch
        (resources.py:1119)."""
        for resources in task.get_preferred_resources():
            if resources.less_demanding_than(handle.launched_resources):
                return
        raise exceptions.ResourcesMismatchError(
            f'Requested resources do not fit cluster '
            f'{handle.cluster_name!r}: requested '
            f'{task.get_preferred_resources()}, cluster has '
            f'{handle.launched_resources}. Use a new cluster name or '
            'relax the request.')

    def _authentication_config(
            self,
            cloud: Optional[Any] = None) -> Dict[str, Any]:
        if cloud is not None and cloud.canonical_name() in ('local', 'fake'):
            return {}  # no SSH needed for process-based/simulated hosts
        from skypilot_tpu import authentication
        pub = authentication.get_or_generate_keys()[1]
        with open(pub, encoding='utf-8') as f:
            pub_key = f.read().strip()
        return {
            'ssh_keys': f'skytpu:{pub_key}',
            'ssh_user': 'skytpu',
        }

    def _ssh_key_path(self,
                      cloud: Optional[Any] = None) -> Optional[str]:
        if cloud is not None and cloud.canonical_name() in ('local', 'fake'):
            return None
        from skypilot_tpu import authentication
        return authentication.get_or_generate_keys()[0]

    def _post_provision_runtime_setup(
            self, handle: backend_lib.ClusterHandle) -> None:
        """Wait for hosts, ship runtime, start the agent daemon
        (reference post_provision_runtime_setup, provisioner.py:631)."""
        runners = [self._runner_for(handle, a)
                   for a in handle.host_addresses]

        def _wait_host(runner: runner_lib.CommandRunner) -> None:
            deadline = time.time() + 300
            while time.time() < deadline:
                if runner.check_connection():
                    return
                time.sleep(3)
            raise exceptions.FetchClusterInfoError(
                exceptions.FetchClusterInfoError.Reason.HEAD)

        subprocess_utils.run_in_parallel(_wait_host, runners)

        if not self._is_local(handle):
            # Ship the framework source to every host (the reference ships a
            # wheel built client-side, wheel_utils.py:1-40; rsyncing the
            # package tree gives the same exact-client-code property).
            import skypilot_tpu
            pkg_dir = os.path.dirname(skypilot_tpu.__file__)

            def _ship(runner: runner_lib.CommandRunner) -> None:
                runner.run(f'mkdir -p ~/{_RUNTIME_DIR}', timeout=60)
                runner.rsync(pkg_dir, f'~/{_RUNTIME_DIR}/skypilot_tpu',
                             up=True, excludes=['__pycache__'])

            subprocess_utils.run_in_parallel(_ship, runners)
            # The head host fans out rank processes to its peers over SSH
            # (gang driver), so the cluster key must live on the head too
            # (reference: internal_file_mounts ships credentials,
            # provisioner.py:503).
            key_path = self._ssh_key_path()
            if key_path is not None:
                head = self._head_runner(handle)
                head.run('mkdir -p ~/.ssh && chmod 700 ~/.ssh', timeout=60)
                head.rsync(key_path, '~/.ssh/skytpu-key', up=True)
                head.run('chmod 600 ~/.ssh/skytpu-key', timeout=60)

        # Agent config (autostop teardown needs provider details).
        agent_config = {
            'provider_name': handle.provider_name,
            'cluster_name_on_cloud': handle.cluster_name_on_cloud,
            'provider_config': handle.provider_config,
        }
        root = handle.head_agent_root
        config_dir = (os.path.join(root, agent_constants.AGENT_DIR)
                      if root else f'~/{agent_constants.AGENT_DIR}')
        head = self._head_runner(handle)
        config_json = json.dumps(agent_config)
        head.run(
            f'mkdir -p {config_dir} && cat > '
            f'{config_dir}/{agent_constants.AGENT_CONFIG} <<\'EOF\'\n'
            f'{config_json}\nEOF', timeout=60)
        self._start_agent_daemon(handle)

    def _start_agent_daemon(self, handle: backend_lib.ClusterHandle) -> None:
        """Start (or restart on version change) the agent daemon on head
        (reference start_skylet_on_head_node, instance_setup.py:440 +
        attempt_skylet version gating)."""
        root = handle.head_agent_root
        root_arg = f'--root {shlex.quote(root)}' if root else ''
        agent_dir = (os.path.join(root, agent_constants.AGENT_DIR)
                     if root else f'$HOME/{agent_constants.AGENT_DIR}')
        pid_file = f'{agent_dir}/{agent_constants.AGENT_PID}'
        log_file = f'{agent_dir}/{agent_constants.AGENT_LOG}'
        version_file = f'{agent_dir}/{agent_constants.AGENT_VERSION_FILE}'
        want = agent_constants.AGENT_VERSION
        # Keep a live daemon only if its recorded version matches the
        # runtime just shipped; otherwise kill it and start fresh
        # (reference attempt_skylet.py restart-on-version-change).
        cmd = (
            f'mkdir -p {agent_dir}; '
            f'have=$(cat {version_file} 2>/dev/null || echo 0); '
            f'if [ -f {pid_file} ] && kill -0 $(cat {pid_file}) '
            f'2>/dev/null && [ "$have" = "{want}" ]; then true; else '
            f'if [ -f {pid_file} ]; then kill $(cat {pid_file}) '
            '2>/dev/null || true; fi; '
            # Control-plane strip (agent/constants.PJRT_STRIP_PREFIX):
            # the daemon never touches jax; the stash keeps the value
            # for user jobs downstream.
            f'{agent_constants.PJRT_STRIP_PREFIX}'
            f'nohup python3 -u -m skypilot_tpu.agent.daemon {root_arg} '
            f'>> {log_file} 2>&1 & fi')
        self.run_on_head(handle, cmd, timeout=60)

    # ------------------------------------------------------------------
    # sync / setup
    # ------------------------------------------------------------------
    def _sync_workdir(self, handle: backend_lib.ClusterHandle,
                      workdir: str) -> None:
        excludes = runner_lib.workdir_excludes(workdir)

        def _sync(address: str) -> None:
            runner = self._runner_for(handle, address)
            target = (agent_constants.WORKDIR
                      if address.startswith('local:')
                      else f'~/{agent_constants.WORKDIR}')
            runner.rsync(workdir, target, up=True, excludes=excludes)

        subprocess_utils.run_in_parallel(_sync, handle.host_addresses)

    def _sync_file_mounts(self, handle: backend_lib.ClusterHandle,
                          all_file_mounts: Optional[Dict[str, str]],
                          storage_mounts: Optional[Dict[str, Any]]) -> None:
        for target, source in (all_file_mounts or {}).items():
            if source.startswith(('s3://', 'gs://', 'gcs://', 'r2://',
                                  'az://', 'http://', 'https://')):
                from skypilot_tpu.data import cloud_stores
                cmd = cloud_stores.make_download_command(source, target)

                def _dl(address: str, cmd=cmd) -> None:
                    runner = self._runner_for(handle, address)
                    rc, out, err = runner.run(cmd, require_outputs=True)
                    if rc != 0:
                        raise exceptions.CommandError(
                            rc, f'download {source}', err or out)

                subprocess_utils.run_in_parallel(_dl,
                                                 handle.host_addresses)
            else:
                def _up(address: str, target=target, source=source) -> None:
                    runner = self._runner_for(handle, address)
                    dst = target
                    if not address.startswith('local:') and \
                            not dst.startswith(('~', '/')):
                        dst = f'~/{dst}'
                    runner.rsync(os.path.expanduser(source), dst, up=True)

                subprocess_utils.run_in_parallel(_up,
                                                 handle.host_addresses)
        for target, storage in (storage_mounts or {}).items():
            from skypilot_tpu.data import storage_mounting
            storage_mounting.mount_storage(self, handle, target, storage)

    def _setup(self, handle: backend_lib.ClusterHandle,
               task: 'task_lib.Task', detach_setup: bool = False) -> None:
        if task.setup is None:
            return
        del detach_setup
        prefix = self._runtime_prefix(handle)
        setup_script = task.setup
        envs = task.envs

        def _run_setup(address: str) -> None:
            runner = self._runner_for(handle, address)
            workdir = (agent_constants.WORKDIR
                       if address.startswith('local:')
                       else f'~/{agent_constants.WORKDIR}')
            cmd = (f'{prefix}mkdir -p {workdir} && cd {workdir} && '
                   f'bash -c {shlex.quote(setup_script)}')
            rc, out, err = runner.run(cmd, env_vars=envs,
                                      require_outputs=True)
            if rc != 0:
                raise exceptions.CommandError(
                    rc, f'setup on {address}',
                    (out or '') + (err or ''))

        logger.info(f'Running setup on {len(handle.host_addresses)} '
                    'host(s).')
        subprocess_utils.run_in_parallel(_run_setup, handle.host_addresses)

    # ------------------------------------------------------------------
    # execute
    # ------------------------------------------------------------------
    def _execute(self, handle: backend_lib.ClusterHandle,
                 task: 'task_lib.Task', detach_run: bool,
                 dryrun: bool = False) -> Optional[int]:
        if dryrun:
            logger.info(f'Dryrun: would execute {task} on '
                        f'{handle.cluster_name!r}.')
            return None
        if task.run is None:
            logger.info('Nothing to run (no run section).')
            return None
        spec = self._make_job_spec(handle, task)
        result = self._rpc(handle, 'add_job', spec=spec)
        job_id = result['job_id']
        self._rpc(handle, 'schedule')
        logger.info(f'Job {job_id} submitted to {handle.cluster_name!r}.'
                    + ('' if detach_run else ' Streaming logs...'))
        self.last_job_exit_code = 0
        if not detach_run:
            # Propagate the job's final status (JobExitCode contract,
            # reference: `sky launch` streams then reflects job failure).
            self.last_job_exit_code = self.tail_logs(handle, job_id,
                                                     follow=True)
        return job_id

    def _make_job_spec(self, handle: backend_lib.ClusterHandle,
                       task: 'task_lib.Task') -> Dict[str, Any]:
        spec_res = handle.launched_resources.tpu_slice
        hosts = []
        for address, internal in zip(handle.host_addresses,
                                     handle.internal_ips):
            hosts.append({
                'address': address,
                'internal_ip': internal,
                'ssh_user': handle.ssh_user,
                'ssh_key': (f'~/.ssh/skytpu-key'
                            if not self._is_local(handle) else None),
            })
        num_hosts = len(hosts)
        if callable(task.run):
            ips = [h['internal_ip'] for h in hosts]
            run_commands = []
            for rank in range(num_hosts):
                cmd = task.run(rank, ips)
                run_commands.append(cmd if cmd else 'true')
        else:
            run_commands = [task.run]
        return {
            'job_name': task.name,
            'username': getpass.getuser(),
            'run_timestamp': time.strftime('%Y-%m-%d-%H-%M-%S'),
            'resources_str': repr(handle.launched_resources),
            'cluster_name': handle.cluster_name,
            'hosts': hosts,
            'num_logical_nodes': handle.launched_nodes,
            'hosts_per_node': handle.num_hosts_per_node,
            'run_commands': run_commands,
            'env_vars': task.envs,
            'accelerator':
                spec_res.accelerator_name if spec_res else None,
            'chips_per_host': spec_res.chips_per_host if spec_res else 0,
            'remote_runtime_prefix': self._runtime_prefix(handle),
        }

    # ------------------------------------------------------------------
    # logs / queue / cancel / autostop
    # ------------------------------------------------------------------
    def tail_logs(self, handle: backend_lib.ClusterHandle,
                  job_id: Optional[int], follow: bool = True,
                  tail: int = 0) -> int:
        root = handle.head_agent_root
        root_arg = shlex.quote(root) if root else '$HOME'
        cmd = (f'{self._runtime_prefix(handle)}'
               f'python3 -u -m skypilot_tpu.agent.log_tail '
               f'--root {root_arg}'
               + (f' --job-id {job_id}' if job_id is not None else '')
               + (' --follow' if follow else '')
               + (f' --tail {tail}' if tail else ''))
        # Stream directly to our stdout/stderr (interactive follow).
        head = self._head_runner(handle)
        if isinstance(head, runner_lib.LocalHostRunner):
            env = dict(os.environ)
            env['SKYTPU_LOCAL_HOST_ROOT'] = head.host_root
            import skypilot_tpu
            pkg_parent = os.path.dirname(
                os.path.dirname(skypilot_tpu.__file__))
            env['PYTHONPATH'] = (pkg_parent + os.pathsep +
                                 env.get('PYTHONPATH', ''))
            proc = subprocess.run(cmd, shell=True, executable='/bin/bash',
                                  env=env, cwd=head.host_root, check=False)
            return proc.returncode
        assert isinstance(head, runner_lib.SSHCommandRunner)
        # pylint: disable=protected-access
        full = head._ssh_base() + [f'{head.ssh_user}@{head.address}', cmd]
        proc = subprocess.run(full, check=False)
        return proc.returncode

    def get_job_queue(self, handle: backend_lib.ClusterHandle
                      ) -> List[Dict[str, Any]]:
        return self._rpc(handle, 'queue')['jobs']

    def get_job_status(self, handle: backend_lib.ClusterHandle,
                       job_ids: List[int]) -> Dict[int, Optional[str]]:
        statuses = self._rpc(handle, 'get_statuses',
                             job_ids=job_ids)['statuses']
        return {int(k): v for k, v in statuses.items()}

    def cancel_jobs(self, handle: backend_lib.ClusterHandle,
                    job_ids: Optional[List[int]] = None,
                    all_jobs: bool = False) -> List[int]:
        return self._rpc(handle, 'cancel', job_ids=job_ids,
                         all=all_jobs)['cancelled']

    def set_autostop(self, handle: backend_lib.ClusterHandle,
                     idle_minutes: int, down: bool = False) -> None:
        spec = handle.launched_resources.tpu_slice
        if spec is not None and spec.is_pod and idle_minutes >= 0 and \
                not down:
            logger.info('TPU pod slices cannot stop; autostop will '
                        'autodown instead.')
            down = True
        self._rpc(handle, 'set_autostop', idle_minutes=idle_minutes,
                  down=down)
        global_user_state.set_cluster_autostop_value(
            handle.cluster_name, idle_minutes, down)

    # ------------------------------------------------------------------
    # teardown
    # ------------------------------------------------------------------
    def _teardown(self, handle: backend_lib.ClusterHandle, terminate: bool,
                  purge: bool = False) -> None:
        if not terminate:
            spec = handle.launched_resources.tpu_slice
            if spec is not None and spec.is_pod:
                raise exceptions.NotSupportedError(
                    'TPU pod slices cannot be stopped; use down/terminate '
                    '(reference parity: sky/clouds/gcp.py:193-204).')
        try:
            provisioner_lib.teardown_cluster(
                handle.provider_name, handle.cluster_name_on_cloud,
                handle.provider_config, terminate=terminate)
        except Exception:  # noqa: BLE001
            if not purge:
                raise
            logger.warning('Teardown failed; purging state anyway.')
        global_user_state.remove_cluster(handle.cluster_name,
                                         terminate=terminate)
