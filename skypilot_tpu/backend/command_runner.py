"""Command runners: uniform exec/rsync to cluster hosts.

Counterpart of the reference's sky/utils/command_runner.py (:168 ABC,
:426 SSHCommandRunner with ControlMaster reuse).  Additions:
  - `LocalHostRunner` executes against a *local host root directory*
    ('local:<dir>' addresses from provision/local) so the identical
    backend/agent code paths drive process-based clusters — the hermetic
    test substrate.
  - `from_address` picks the runner from the address scheme.
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

SSH_CONTROL_DIR = '/tmp/skytpu_ssh_control'


def _expand(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


class CommandRunner:
    """Execute commands / sync files on one cluster host."""

    def __init__(self, address: str) -> None:
        self.address = address

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env_vars: Optional[Dict[str, str]] = None,
            require_outputs: bool = False,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            cwd: Optional[str] = None,
            timeout: Optional[float] = None
            ) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              excludes: Optional[List[str]] = None) -> None:
        raise NotImplementedError

    @staticmethod
    def _shell_command(cmd: Union[str, List[str]],
                       env_vars: Optional[Dict[str, str]],
                       cwd: Optional[str]) -> str:
        """One bash command string: env exports + cd + the command
        (shared by every runner so quoting fixes land once)."""
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        exports = ''.join(
            f'export {k}={shlex.quote(str(v))}; '
            for k, v in (env_vars or {}).items())
        if cwd and cwd.startswith('~/'):
            # '~' must stay outside the quotes to expand remotely.
            cd = f'cd "$HOME"/{shlex.quote(cwd[2:])}; '
        elif cwd:
            cd = f'cd {shlex.quote(cwd)}; '
        else:
            cd = ''
        return exports + cd + cmd

    @staticmethod
    def _finish(proc: 'subprocess.CompletedProcess', log_path: str,
                stream_logs: bool, require_outputs: bool):
        text = (proc.stdout or '') + (proc.stderr or '')
        if log_path not in ('/dev/null', None) and text:
            os.makedirs(os.path.dirname(_expand(log_path)),
                        exist_ok=True)
            with open(_expand(log_path), 'a', encoding='utf-8') as f:
                f.write(text)
        if stream_logs and text:
            # skylint: disable=stdout-purity (relaying remote output)
            print(text, end='')
        if require_outputs:
            return proc.returncode, proc.stdout, proc.stderr
        return proc.returncode

    # -- tar-over-exec sync (shared by k8s + docker runners) --------------
    def _exec_argv(self, interactive: bool) -> List[str]:
        """argv prefix that execs `/bin/bash -c <cmd>` in the remote
        substrate; the shell command is appended as the last element."""
        raise NotImplementedError

    def _tar_over_exec_rsync(self, source: str, target: str, *, up: bool,
                             excludes: Optional[List[str]] = None) -> None:
        """rsync-equivalent file sync streamed through an exec channel
        (`kubectl exec` / `docker exec`): dirs merge into the target
        directory; a single file lands AT the target path (rsync
        semantics, so all runner substrates behave identically).
        Relative targets are rooted at the remote $HOME."""
        exclude_args = ' '.join(
            f'--exclude={shlex.quote(pat)}' for pat in excludes or [])

        def argv_str(interactive: bool, remote_cmd: str) -> str:
            return ' '.join(
                shlex.quote(a)
                for a in self._exec_argv(interactive) + [remote_cmd])

        if up:
            src = _expand(source)
            if not target.startswith(('/', '~')):
                target = f'~/{target}'
            remote_target = target.replace('~', '$HOME', 1)
            if os.path.isdir(src):
                tar_src = f'-C {shlex.quote(src)} .'
                remote_cmd = (f'mkdir -p "{remote_target}" && '
                              f'tar xzf - -C "{remote_target}"')
            else:
                src_dir, src_base = os.path.split(src)
                tar_src = (f'-C {shlex.quote(src_dir)} '
                           f'{shlex.quote(src_base)}')
                # File destination: the target IS the file path.
                remote_cmd = (
                    f'dst="{remote_target}"; '
                    f'mkdir -p "$(dirname "$dst")" && '
                    f'tar xzf - -C "$(dirname "$dst")" && '
                    f'if [ "$(basename "$dst")" != '
                    f'{shlex.quote(src_base)} ]; then '
                    f'mv "$(dirname "$dst")/"{shlex.quote(src_base)} '
                    f'"$dst"; fi')
            full = (f'tar czf - {exclude_args} {tar_src} | '
                    + argv_str(True, remote_cmd))
        else:
            if not source.startswith(('/', '~')):
                source = f'~/{source}'
            remote_src = source.replace('~', '$HOME', 1)
            src_base = source.rstrip('/').rsplit('/', 1)[-1]
            dst = _expand(target)
            # rsync semantics: an existing-dir (or trailing-slash)
            # target receives the entry under its remote basename; any
            # other target IS the destination path (renamed).
            if os.path.isdir(dst) or target.endswith('/'):
                out_dir, final = dst, None
            else:
                out_dir = os.path.dirname(dst) or '.'
                final = dst
            os.makedirs(out_dir, exist_ok=True)
            remote_cmd = (f'cd "$(dirname "{remote_src}")" && '
                          f'tar czf - "$(basename "{remote_src}")"')
            full = (argv_str(False, remote_cmd)
                    + f' | tar xzf - -C {shlex.quote(out_dir)}')
        proc = subprocess.run(full, shell=True, executable='/bin/bash',
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode,
                f'tar-over-exec sync to {self.address}', proc.stderr)
        if not up and final is not None and \
                os.path.basename(final) != src_base:
            os.replace(os.path.join(out_dir, src_base), final)

    def check_connection(self) -> bool:
        try:
            rc = self.run('true', timeout=10)
            return rc == 0
        except Exception:  # noqa: BLE001
            return False

    @classmethod
    def from_address(cls, address: str,
                     ssh_user: Optional[str] = None,
                     ssh_key: Optional[str] = None,
                     port: int = 22) -> 'CommandRunner':
        if address.startswith('local:'):
            return LocalHostRunner(address)
        if address.startswith('k8s:'):
            return KubernetesPodRunner(address)
        if address.startswith('docker:'):
            return DockerContainerRunner(address)
        return SSHCommandRunner(address, ssh_user=ssh_user, ssh_key=ssh_key,
                                port=port)


class LocalHostRunner(CommandRunner):
    """Run commands rooted at a local host directory (simulated host).

    The host dir acts as the host's home: commands get
    SKYTPU_LOCAL_HOST_ROOT pointing at it (used by the local provisioner's
    process reaper and by the agent to find its state dir).
    """

    def __init__(self, address: str) -> None:
        super().__init__(address)
        assert address.startswith('local:'), address
        self.host_root = address[len('local:'):]

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        env = dict(os.environ)
        env.update(env_vars or {})
        env['SKYTPU_LOCAL_HOST_ROOT'] = self.host_root
        # Make this skypilot_tpu importable in child processes regardless of
        # cwd/install mode (local hosts share the client's filesystem).
        import skypilot_tpu
        pkg_parent = os.path.dirname(os.path.dirname(skypilot_tpu.__file__))
        existing = env.get('PYTHONPATH', '')
        if pkg_parent not in existing.split(os.pathsep):
            env['PYTHONPATH'] = (pkg_parent + os.pathsep + existing
                                 if existing else pkg_parent)
        os.makedirs(self.host_root, exist_ok=True)
        proc = subprocess.run(
            cmd, shell=True, executable='/bin/bash',
            cwd=cwd or self.host_root, env=env,
            capture_output=True, text=True, timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        if up:
            src, dst = _expand(source), os.path.join(self.host_root,
                                                     target.lstrip('/'))
        else:
            src = os.path.join(self.host_root, source.lstrip('/'))
            dst = _expand(target)
        if shutil.which('rsync'):
            exclude_args = []
            for pat in excludes or []:
                exclude_args += ['--exclude', pat]
            src_arg = src + '/' if os.path.isdir(src) else src
            os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
            dst_arg = dst if not os.path.isdir(src) else dst + '/'
            proc = subprocess.run(
                ['rsync', '-a', '--delete', *exclude_args, src_arg, dst_arg],
                capture_output=True, text=True, check=False)
            if proc.returncode != 0:
                raise exceptions.CommandError(proc.returncode, 'rsync',
                                              proc.stderr)
        else:
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
                shutil.copy2(src, dst)


class SSHCommandRunner(CommandRunner):
    """SSH + rsync with ControlMaster connection reuse (reference
    command_runner.py:426)."""

    def __init__(self, address: str, ssh_user: Optional[str] = None,
                 ssh_key: Optional[str] = None, port: int = 22,
                 ssh_proxy_command: Optional[str] = None) -> None:
        super().__init__(address)
        self.ssh_user = ssh_user or 'skytpu'
        self.ssh_key = ssh_key
        self.port = port
        self.ssh_proxy_command = ssh_proxy_command
        os.makedirs(SSH_CONTROL_DIR, exist_ok=True)

    def _ssh_base(self) -> List[str]:
        args = [
            'ssh', '-T',
            '-o', 'StrictHostKeyChecking=no',
            '-o', 'UserKnownHostsFile=/dev/null',
            '-o', 'LogLevel=ERROR',
            '-o', 'IdentitiesOnly=yes',
            '-o', 'ConnectTimeout=30',
            '-o', 'ServerAliveInterval=20',
            '-o', 'ServerAliveCountMax=3',
            '-o', f'ControlPath={SSH_CONTROL_DIR}/%C',
            '-o', 'ControlMaster=auto',
            '-o', 'ControlPersist=300s',
            '-p', str(self.port),
        ]
        if self.ssh_key:
            args += ['-i', _expand(self.ssh_key)]
        if self.ssh_proxy_command:
            args += ['-o', f'ProxyCommand={self.ssh_proxy_command}']
        return args

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None, timeout=None):
        remote = ('bash -c ' +
                  shlex.quote(self._shell_command(cmd, env_vars, cwd)))
        full = self._ssh_base() + [f'{self.ssh_user}@{self.address}', remote]
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        ssh_cmd = ' '.join(
            shlex.quote(a) for a in self._ssh_base())
        exclude_args = []
        for pat in excludes or []:
            exclude_args += ['--exclude', pat]
        remote = f'{self.ssh_user}@{self.address}'
        if up:
            src_arg = _expand(source)
            if os.path.isdir(src_arg):
                src_arg += '/'
            pair = [src_arg, f'{remote}:{target}']
        else:
            pair = [f'{remote}:{source}', _expand(target)]
        proc = subprocess.run(
            ['rsync', '-az', '--delete', '-e', ssh_cmd, *exclude_args,
             *pair],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode, f'rsync to {self.address}', proc.stderr)


class KubernetesPodRunner(CommandRunner):
    """`kubectl exec`-based runner for GKE pods (reference
    KubernetesCommandRunner, sky/utils/command_runner.py:685).

    Address scheme: 'k8s:<context>/<namespace>/<pod>' (context may be
    empty for the kubeconfig default).  File sync uses `kubectl cp`
    (tar under the hood) instead of rsync.
    """

    def __init__(self, address: str) -> None:
        super().__init__(address)
        assert address.startswith('k8s:'), address
        context, namespace, pod = address[len('k8s:'):].split('/', 2)
        self.context = context or None
        self.namespace = namespace
        self.pod = pod

    def _base(self) -> List[str]:
        args = ['kubectl']
        if self.context:
            args += ['--context', self.context]
        args += ['--namespace', self.namespace]
        return args

    def _exec_argv(self, interactive: bool) -> List[str]:
        return (self._base() + ['exec']
                + (['-i'] if interactive else [])
                + [self.pod, '--', '/bin/bash', '-c'])

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None,
            timeout=None):
        full = self._exec_argv(False) + [
            self._shell_command(cmd, env_vars, cwd)]
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None):
        """Tar streamed through `kubectl exec` (NOT kubectl cp: cp
        neither expands '~' in pod paths nor supports excludes, and the
        backend syncs to ~-prefixed targets with gitignore excludes)."""
        self._tar_over_exec_rsync(source, target, up=up,
                                  excludes=excludes)


class DockerContainerRunner(CommandRunner):
    """`docker exec`-based runner for local containers (reference:
    sky/backends/docker_utils.py + DOCKER_IMAGE feature, cloud.py:29-50).

    Address scheme: 'docker:<container>'.  File sync streams tar
    through `docker exec -i`, mirroring the Kubernetes runner, so '~'
    targets and excludes behave identically across substrates.
    """

    def __init__(self, address: str) -> None:
        super().__init__(address)
        assert address.startswith('docker:'), address
        self.container = address[len('docker:'):]

    def _exec_argv(self, interactive: bool) -> List[str]:
        return (['docker', 'exec']
                + (['-i'] if interactive else [])
                + [self.container, '/bin/bash', '-c'])

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None,
            timeout=None):
        full = self._exec_argv(False) + [
            self._shell_command(cmd, env_vars, cwd)]
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None):
        self._tar_over_exec_rsync(source, target, up=up,
                                  excludes=excludes)


def workdir_excludes(source_dir: str) -> List[str]:
    """Exclusion patterns for workdir sync: .git plus .skytpuignore/.gitignore
    entries (reference: rsync + git-ignore handling,
    cloud_vm_ray_backend.py:3137)."""
    excludes = ['.git']
    for ignore_file in ('.skytpuignore', '.gitignore'):
        path = os.path.join(_expand(source_dir), ignore_file)
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith('#') and \
                            not line.startswith('!'):
                        excludes.append(line)
            break  # .skytpuignore wins over .gitignore
    return excludes
