"""Command runners: uniform exec/rsync to cluster hosts.

Counterpart of the reference's sky/utils/command_runner.py (:168 ABC,
:426 SSHCommandRunner with ControlMaster reuse).  Additions:
  - `LocalHostRunner` executes against a *local host root directory*
    ('local:<dir>' addresses from provision/local) so the identical
    backend/agent code paths drive process-based clusters — the hermetic
    test substrate.
  - `from_address` picks the runner from the address scheme.
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

SSH_CONTROL_DIR = '/tmp/skytpu_ssh_control'


def _expand(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


class CommandRunner:
    """Execute commands / sync files on one cluster host."""

    def __init__(self, address: str) -> None:
        self.address = address

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env_vars: Optional[Dict[str, str]] = None,
            require_outputs: bool = False,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            cwd: Optional[str] = None,
            timeout: Optional[float] = None
            ) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              excludes: Optional[List[str]] = None) -> None:
        raise NotImplementedError

    @staticmethod
    def _shell_command(cmd: Union[str, List[str]],
                       env_vars: Optional[Dict[str, str]],
                       cwd: Optional[str]) -> str:
        """One bash command string: env exports + cd + the command
        (shared by every runner so quoting fixes land once)."""
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        exports = ''.join(
            f'export {k}={shlex.quote(str(v))}; '
            for k, v in (env_vars or {}).items())
        cd = f'cd {shlex.quote(cwd)}; ' if cwd else ''
        return exports + cd + cmd

    @staticmethod
    def _finish(proc: 'subprocess.CompletedProcess', log_path: str,
                stream_logs: bool, require_outputs: bool):
        text = (proc.stdout or '') + (proc.stderr or '')
        if log_path not in ('/dev/null', None) and text:
            os.makedirs(os.path.dirname(_expand(log_path)),
                        exist_ok=True)
            with open(_expand(log_path), 'a', encoding='utf-8') as f:
                f.write(text)
        if stream_logs and text:
            print(text, end='')
        if require_outputs:
            return proc.returncode, proc.stdout, proc.stderr
        return proc.returncode

    def check_connection(self) -> bool:
        try:
            rc = self.run('true', timeout=10)
            return rc == 0
        except Exception:  # noqa: BLE001
            return False

    @classmethod
    def from_address(cls, address: str,
                     ssh_user: Optional[str] = None,
                     ssh_key: Optional[str] = None,
                     port: int = 22) -> 'CommandRunner':
        if address.startswith('local:'):
            return LocalHostRunner(address)
        if address.startswith('k8s:'):
            return KubernetesPodRunner(address)
        return SSHCommandRunner(address, ssh_user=ssh_user, ssh_key=ssh_key,
                                port=port)


class LocalHostRunner(CommandRunner):
    """Run commands rooted at a local host directory (simulated host).

    The host dir acts as the host's home: commands get
    SKYTPU_LOCAL_HOST_ROOT pointing at it (used by the local provisioner's
    process reaper and by the agent to find its state dir).
    """

    def __init__(self, address: str) -> None:
        super().__init__(address)
        assert address.startswith('local:'), address
        self.host_root = address[len('local:'):]

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None, timeout=None):
        if isinstance(cmd, list):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        env = dict(os.environ)
        env.update(env_vars or {})
        env['SKYTPU_LOCAL_HOST_ROOT'] = self.host_root
        # Make this skypilot_tpu importable in child processes regardless of
        # cwd/install mode (local hosts share the client's filesystem).
        import skypilot_tpu
        pkg_parent = os.path.dirname(os.path.dirname(skypilot_tpu.__file__))
        existing = env.get('PYTHONPATH', '')
        if pkg_parent not in existing.split(os.pathsep):
            env['PYTHONPATH'] = (pkg_parent + os.pathsep + existing
                                 if existing else pkg_parent)
        os.makedirs(self.host_root, exist_ok=True)
        proc = subprocess.run(
            cmd, shell=True, executable='/bin/bash',
            cwd=cwd or self.host_root, env=env,
            capture_output=True, text=True, timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        if up:
            src, dst = _expand(source), os.path.join(self.host_root,
                                                     target.lstrip('/'))
        else:
            src = os.path.join(self.host_root, source.lstrip('/'))
            dst = _expand(target)
        if shutil.which('rsync'):
            exclude_args = []
            for pat in excludes or []:
                exclude_args += ['--exclude', pat]
            src_arg = src + '/' if os.path.isdir(src) else src
            os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
            dst_arg = dst if not os.path.isdir(src) else dst + '/'
            proc = subprocess.run(
                ['rsync', '-a', '--delete', *exclude_args, src_arg, dst_arg],
                capture_output=True, text=True, check=False)
            if proc.returncode != 0:
                raise exceptions.CommandError(proc.returncode, 'rsync',
                                              proc.stderr)
        else:
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
                shutil.copy2(src, dst)


class SSHCommandRunner(CommandRunner):
    """SSH + rsync with ControlMaster connection reuse (reference
    command_runner.py:426)."""

    def __init__(self, address: str, ssh_user: Optional[str] = None,
                 ssh_key: Optional[str] = None, port: int = 22,
                 ssh_proxy_command: Optional[str] = None) -> None:
        super().__init__(address)
        self.ssh_user = ssh_user or 'skytpu'
        self.ssh_key = ssh_key
        self.port = port
        self.ssh_proxy_command = ssh_proxy_command
        os.makedirs(SSH_CONTROL_DIR, exist_ok=True)

    def _ssh_base(self) -> List[str]:
        args = [
            'ssh', '-T',
            '-o', 'StrictHostKeyChecking=no',
            '-o', 'UserKnownHostsFile=/dev/null',
            '-o', 'LogLevel=ERROR',
            '-o', 'IdentitiesOnly=yes',
            '-o', 'ConnectTimeout=30',
            '-o', 'ServerAliveInterval=20',
            '-o', 'ServerAliveCountMax=3',
            '-o', f'ControlPath={SSH_CONTROL_DIR}/%C',
            '-o', 'ControlMaster=auto',
            '-o', 'ControlPersist=300s',
            '-p', str(self.port),
        ]
        if self.ssh_key:
            args += ['-i', _expand(self.ssh_key)]
        if self.ssh_proxy_command:
            args += ['-o', f'ProxyCommand={self.ssh_proxy_command}']
        return args

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None, timeout=None):
        remote = ('bash -c ' +
                  shlex.quote(self._shell_command(cmd, env_vars, cwd)))
        full = self._ssh_base() + [f'{self.ssh_user}@{self.address}', remote]
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool, excludes=None):
        ssh_cmd = ' '.join(
            shlex.quote(a) for a in self._ssh_base())
        exclude_args = []
        for pat in excludes or []:
            exclude_args += ['--exclude', pat]
        remote = f'{self.ssh_user}@{self.address}'
        if up:
            src_arg = _expand(source)
            if os.path.isdir(src_arg):
                src_arg += '/'
            pair = [src_arg, f'{remote}:{target}']
        else:
            pair = [f'{remote}:{source}', _expand(target)]
        proc = subprocess.run(
            ['rsync', '-az', '--delete', '-e', ssh_cmd, *exclude_args,
             *pair],
            capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode, f'rsync to {self.address}', proc.stderr)


class KubernetesPodRunner(CommandRunner):
    """`kubectl exec`-based runner for GKE pods (reference
    KubernetesCommandRunner, sky/utils/command_runner.py:685).

    Address scheme: 'k8s:<context>/<namespace>/<pod>' (context may be
    empty for the kubeconfig default).  File sync uses `kubectl cp`
    (tar under the hood) instead of rsync.
    """

    def __init__(self, address: str) -> None:
        super().__init__(address)
        assert address.startswith('k8s:'), address
        context, namespace, pod = address[len('k8s:'):].split('/', 2)
        self.context = context or None
        self.namespace = namespace
        self.pod = pod

    def _base(self) -> List[str]:
        args = ['kubectl']
        if self.context:
            args += ['--context', self.context]
        args += ['--namespace', self.namespace]
        return args

    def run(self, cmd, *, env_vars=None, require_outputs=False,
            log_path='/dev/null', stream_logs=False, cwd=None,
            timeout=None):
        full = self._base() + [
            'exec', self.pod, '--', '/bin/bash', '-c',
            self._shell_command(cmd, env_vars, cwd)]
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, check=False)
        return self._finish(proc, log_path, stream_logs, require_outputs)

    def rsync(self, source: str, target: str, *, up: bool,
              excludes=None):
        """Tar streamed through `kubectl exec` (NOT kubectl cp: cp
        neither expands '~' in pod paths nor supports excludes, and the
        backend syncs to ~-prefixed targets with gitignore excludes)."""
        exclude_args = ' '.join(
            f'--exclude={shlex.quote(pat)}' for pat in excludes or [])
        if up:
            src = _expand(source)
            if os.path.isdir(src):
                tar_src = f'-C {shlex.quote(src)} .'
            else:
                tar_src = (f'-C {shlex.quote(os.path.dirname(src))} '
                           f'{shlex.quote(os.path.basename(src))}')
            # $HOME expands inside the pod's bash.
            remote_dir = target.replace('~', '$HOME', 1)
            local_cmd = f'tar czf - {exclude_args} {tar_src}'
            remote_cmd = (f'mkdir -p "{remote_dir}" && '
                          f'tar xzf - -C "{remote_dir}"')
            full = (f'{local_cmd} | ' + ' '.join(
                shlex.quote(a) for a in self._base() +
                ['exec', '-i', self.pod, '--', '/bin/bash', '-c',
                 remote_cmd]))
        else:
            remote_src = source.replace('~', '$HOME', 1)
            dst = _expand(target)
            os.makedirs(dst if not os.path.splitext(dst)[1] else
                        os.path.dirname(dst), exist_ok=True)
            remote_cmd = (f'cd "$(dirname "{remote_src}")" && '
                          f'tar czf - "$(basename "{remote_src}")"')
            full = (' '.join(shlex.quote(a) for a in self._base() +
                             ['exec', self.pod, '--', '/bin/bash', '-c',
                              remote_cmd]) +
                    f' | tar xzf - -C {shlex.quote(dst)}')
        proc = subprocess.run(full, shell=True, executable='/bin/bash',
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode, f'tar-over-exec sync to {self.pod}',
                proc.stderr)


def workdir_excludes(source_dir: str) -> List[str]:
    """Exclusion patterns for workdir sync: .git plus .skytpuignore/.gitignore
    entries (reference: rsync + git-ignore handling,
    cloud_vm_ray_backend.py:3137)."""
    excludes = ['.git']
    for ignore_file in ('.skytpuignore', '.gitignore'):
        path = os.path.join(_expand(source_dir), ignore_file)
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith('#') and \
                            not line.startswith('!'):
                        excludes.append(line)
            break  # .skytpuignore wins over .gitignore
    return excludes
