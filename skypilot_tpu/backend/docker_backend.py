"""Local-docker backend: run tasks in containers on this machine.

Counterpart of the reference's sky/backends/local_docker_backend.py
(646 LoC with docker_utils.py): an alternate `Backend` that "provisions"
a local container instead of a cloud cluster — the zero-cloud dev loop
for task images.  Parity notes, same as the reference's documented
limitations: no job queue/autostop (execute is blocking or detached via
nohup inside the container), one node.

The container substrate is driven entirely through the `docker` CLI
(DockerContainerRunner) so tests can shim a fake `docker` on PATH; no
docker SDK dependency.

Resources opt in with image_id='docker:<image>' (the DOCKER_IMAGE
feature flag, reference cloud.py:29-50).
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.backend import command_runner
from skypilot_tpu.utils import paths

logger = sky_logging.init_logger(__name__)

_CONTAINER_PREFIX = 'skytpu-docker-'
_LABEL = 'skytpu.cluster'
DEFAULT_IMAGE = 'ubuntu:22.04'


def docker_image_from_resources(
        resources: Optional[resources_lib.Resources]) -> Optional[str]:
    """The explicitly requested image, or None (no preference — a
    relaunch onto an existing container keeps whatever it runs)."""
    image_id = getattr(resources, 'image_id', None) if resources else None
    if image_id and image_id.startswith('docker:'):
        return image_id[len('docker:'):]
    return None


def container_name(cluster_name: str) -> str:
    return _CONTAINER_PREFIX + cluster_name


def _docker(*args: str, check: bool = True,
            timeout: Optional[float] = 600) -> 'subprocess.CompletedProcess':
    proc = subprocess.run(['docker', *args], capture_output=True,
                          text=True, timeout=timeout, check=False)
    if check and proc.returncode != 0:
        raise exceptions.CommandError(
            proc.returncode, 'docker ' + ' '.join(args), proc.stderr)
    return proc


def docker_available() -> bool:
    if shutil.which('docker') is None:
        return False
    try:
        return _docker('version', '--format', '{{.Server.Os}}',
                       check=False, timeout=10).returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


class LocalDockerBackend(backend_lib.Backend):
    """Reference LocalDockerBackend redone over the docker CLI."""

    NAME = 'local_docker'

    def _runner(self,
                handle: backend_lib.ClusterHandle
                ) -> command_runner.DockerContainerRunner:
        runner = command_runner.CommandRunner.from_address(
            handle.head_address)
        assert isinstance(runner, command_runner.DockerContainerRunner)
        return runner

    # -- lifecycle ---------------------------------------------------------
    def _provision(self, task, to_provision, dryrun, stream_logs,
                   cluster_name, retry_until_up):
        if task.num_nodes != 1:
            raise exceptions.NotSupportedError(
                'local_docker backend is single-node (reference '
                'local_docker_backend.py limitation).')
        requested = docker_image_from_resources(to_provision)
        image = requested or DEFAULT_IMAGE
        if dryrun:
            logger.info(f'Dryrun: would run container {image!r} as '
                        f'{container_name(cluster_name)!r}.')
            return None
        if not docker_available():
            raise exceptions.ProvisionError(
                'docker CLI not found or daemon unreachable; the '
                'local_docker backend needs a working `docker`.')
        name = container_name(cluster_name)
        # Idempotent relaunch: a running container is reused unless a
        # *different* image was explicitly requested (no request — e.g.
        # the optimizer was skipped because the cluster is UP — never
        # destroys container state).
        existing = _docker('ps', '-a', '--filter', f'name=^{name}$',
                           '--format', '{{.Image}} {{.State}}',
                           check=False).stdout.strip()
        if existing:
            ex_image, _, state = existing.partition(' ')
            state = state.strip()
            if requested is not None and requested != ex_image:
                _docker('rm', '-f', name, check=False)
                existing = ''
            elif state == 'running':
                logger.info(f'Reusing running container {name!r}.')
                image = ex_image
            else:
                # `sky start` of a stopped container: restart in place,
                # preserving container state (docker analog of
                # resume_stopped_nodes).
                _docker('start', name)
                image = ex_image
        if not existing:
            _docker('run', '-d', '--name', name,
                    '--label', f'{_LABEL}={cluster_name}',
                    image, 'sleep', 'infinity')
            # The run/setup cwd must exist even when no workdir is
            # synced.
            _docker('exec', name, '/bin/bash', '-c',
                    'mkdir -p ~/sky_workdir')
        handle = backend_lib.ClusterHandle(
            cluster_name=cluster_name,
            cluster_name_on_cloud=name,
            provider_name='local_docker',
            provider_config={'image': image},
            launched_nodes=1,
            launched_resources=(to_provision or
                                resources_lib.Resources(cloud='local')),
            host_addresses=[f'docker:{name}'],
            internal_ips=['127.0.0.1'],
        )
        global_user_state.add_or_update_cluster(
            cluster_name, handle, {to_provision} if to_provision else None,
            ready=True)
        return handle

    def _sync_workdir(self, handle, workdir):
        self._runner(handle).rsync(
            workdir, '~/sky_workdir', up=True,
            excludes=command_runner.workdir_excludes(workdir))

    def _sync_file_mounts(self, handle, all_file_mounts, storage_mounts):
        runner = self._runner(handle)
        for dst, src in (all_file_mounts or {}).items():
            if not os.path.exists(os.path.expanduser(src)):
                raise exceptions.CommandError(
                    1, f'file_mount {dst}',
                    f'source {src!r} does not exist.')
            runner.rsync(src, dst, up=True)
        if storage_mounts:
            raise exceptions.NotSupportedError(
                'storage_mounts need FUSE; unsupported inside the '
                'local_docker backend (reference parity).')

    def _log_path(self, handle: backend_lib.ClusterHandle) -> str:
        d = os.path.join(paths.logs_dir(), 'docker',
                         handle.cluster_name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, 'run.log')

    def _setup(self, handle, task, detach_setup):
        if not task.setup:
            return
        rc = self._runner(handle).run(
            task.setup, env_vars=task.envs, cwd='~/sky_workdir',
            log_path=self._log_path(handle), stream_logs=True)
        if rc != 0:
            raise exceptions.CommandError(
                rc, 'task setup (docker)', f'see {self._log_path(handle)}')

    def _execute(self, handle, task, detach_run, dryrun):
        if dryrun or not task.run:
            return None
        env = dict(task.envs or {})
        # Single-node rank contract, same names the gang driver injects.
        env.update({'SKYTPU_NODE_RANK': '0', 'SKYTPU_NUM_NODES': '1',
                    'SKYTPU_NODE_IPS': '127.0.0.1'})
        runner = self._runner(handle)
        if detach_run:
            inner = command_runner.CommandRunner._shell_command(
                task.run, env, '~/sky_workdir')
            rc = runner.run(
                f'nohup bash -c {shlex.quote(inner)} '
                f'> ~/skytpu_run.log 2>&1 & echo started')
            if rc != 0:
                raise exceptions.CommandError(rc, 'detached run (docker)',
                                              '')
            return None
        rc = runner.run(task.run, env_vars=env, cwd='~/sky_workdir',
                        log_path=self._log_path(handle), stream_logs=True)
        if rc != 0:
            raise exceptions.CommandError(
                rc, 'task run (docker)', f'see {self._log_path(handle)}')
        return None

    def _teardown(self, handle, terminate, purge):
        name = handle.cluster_name_on_cloud
        try:
            if terminate:
                _docker('rm', '-f', name, check=False)
                global_user_state.remove_cluster(handle.cluster_name,
                                                 terminate=True)
            else:
                _docker('stop', name)
                global_user_state.update_cluster_status(
                    handle.cluster_name,
                    global_user_state.ClusterStatus.STOPPED)
        except exceptions.CommandError:
            if not purge:
                raise
            global_user_state.remove_cluster(handle.cluster_name,
                                             terminate=True)

    def set_autostop(self, handle, idle_minutes, down=False):
        raise exceptions.NotSupportedError(
            'autostop is not supported by the local_docker backend '
            '(reference parity: local_docker_backend.py has no skylet).')

    # No agent runs in the container, so there is no job queue —
    # reference parity: LocalDockerBackend has no skylet/job table.
    def get_job_queue(self, handle):
        raise exceptions.NotSupportedError(
            'job queue is not supported by the local_docker backend.')

    def cancel_jobs(self, handle, job_ids=None, all_jobs=False):
        raise exceptions.NotSupportedError(
            'job cancel is not supported by the local_docker backend.')

    def tail_logs(self, handle, job_id=None, follow=True, tail=0):
        raise exceptions.NotSupportedError(
            'log tailing is not supported by the local_docker backend; '
            'run logs stream during execute.')

    def get_job_status(self, handle, job_ids=None):
        raise exceptions.NotSupportedError(
            'job status is not supported by the local_docker backend.')

    # -- queries -----------------------------------------------------------
    def query_status(self, handle: backend_lib.ClusterHandle
                     ) -> Optional[str]:
        out = _docker('ps', '-a', '--filter',
                      f'name=^{handle.cluster_name_on_cloud}$',
                      '--format', '{{.State}}', check=False).stdout.strip()
        return out or None

    def list_containers(self) -> Dict[str, Any]:
        out = _docker('ps', '-a', '--filter', f'label={_LABEL}',
                      '--format',
                      '{{.Names}}\t{{.Label "skytpu.cluster"}}\t'
                      '{{.State}}', check=False).stdout
        result = {}
        for line in out.splitlines():
            parts = line.split('\t')
            if len(parts) == 3:
                result[parts[1]] = {'container': parts[0],
                                    'state': parts[2]}
        return result
