"""Usage telemetry: what ran, how long, and how it failed — never code.

Counterpart of the reference's sky/usage/usage_lib.py (496 LoC:
`@usage_lib.entrypoint` wrapping every public API, a `MessageToReport`
schema POSTed to a Grafana Loki endpoint, opt-out via
SKYPILOT_DISABLE_USAGE_COLLECTION).  Redesigned for this stack:

- **Spool-first transport.** Messages are always appended to a local
  JSONL spool (`<state>/usage/messages.jsonl`) and only POSTed when an
  endpoint is explicitly configured (`SKYTPU_USAGE_ENDPOINT`), so the
  subsystem is fully functional — and testable — with zero egress.
  Delivery is best-effort with a short timeout and never raises into
  the user's operation.
- **Privacy.** User code never leaves the machine: task `run`/`setup`
  are reported as line counts, envs as key names only, file_mounts as a
  count.  The user is identified by the existing random hash
  (utils/common_utils.get_user_hash), matching the reference's
  anonymization.
- Opt-out: SKYTPU_DISABLE_USAGE_COLLECTION=1
  (utils/env_options.Options.DISABLE_LOGGING) makes every call a no-op.

The outermost @entrypoint on the call stack owns the message; nested
entrypoints are recorded in its `api_calls` trail (same semantics as
the reference's `entrypoint_context` re-entrancy guard).
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import env_options
from skypilot_tpu.utils import paths

logger = sky_logging.init_logger(__name__)

SCHEMA_VERSION = 1
_ENDPOINT_ENV = 'SKYTPU_USAGE_ENDPOINT'
_POST_TIMEOUT_SECONDS = 2.0
_SPOOL_MAX_BYTES = 4 * 1024 * 1024  # rotate the spool past this size


def _disabled() -> bool:
    return env_options.Options.DISABLE_LOGGING.get()


@dataclasses.dataclass
class UsageMessage:
    """One reported operation (reference UsageMessageToReport)."""
    schema_version: int = SCHEMA_VERSION
    run_id: str = ''
    user_hash: str = ''
    client_version: str = ''
    entrypoint: str = ''
    api_calls: List[str] = dataclasses.field(default_factory=list)
    cluster_names: List[str] = dataclasses.field(default_factory=list)
    task_summary: Optional[Dict[str, Any]] = None
    start_time: float = 0.0
    duration_seconds: Optional[float] = None
    exception_type: Optional[str] = None
    exception_module: Optional[str] = None
    ok: Optional[bool] = None

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _State(threading.local):

    def __init__(self) -> None:
        self.message: Optional[UsageMessage] = None
        self.depth = 0


_state = _State()


_run_counter = itertools.count()


def _new_message(name: str) -> UsageMessage:
    from skypilot_tpu import __version__
    return UsageMessage(
        run_id=(f'{int(time.time()*1000):x}-{os.getpid():x}'
                f'-{next(_run_counter)}'),
        user_hash=common_utils.get_user_hash(),
        client_version=__version__,
        entrypoint=name,
        start_time=time.time(),
    )


def messages() -> Optional[UsageMessage]:
    """The in-flight message of this thread (None outside entrypoints)."""
    return _state.message


def record_cluster_name(name: Optional[str]) -> None:
    m = _state.message
    if m is not None and name and name not in m.cluster_names:
        m.cluster_names.append(name)


def record_task(task: Any) -> None:
    """Attach a privacy-scrubbed task summary (reference _clean_yaml)."""
    m = _state.message
    if m is None or m.task_summary is not None:
        return
    try:
        resources = [str(r) for r in task.get_preferred_resources()]
    except Exception:  # pylint: disable=broad-except
        resources = []
    run = task.run if isinstance(getattr(task, 'run', None), str) else None
    setup = task.setup if isinstance(getattr(task, 'setup', None),
                                     str) else None
    m.task_summary = {
        'num_nodes': getattr(task, 'num_nodes', None),
        'resources': resources,
        'run_lines': len(run.splitlines()) if run else 0,
        'setup_lines': len(setup.splitlines()) if setup else 0,
        'env_keys': sorted((getattr(task, 'envs', None) or {}).keys()),
        'num_file_mounts': len(getattr(task, 'file_mounts', None) or {}),
    }


def record_exception(exc: BaseException) -> None:
    m = _state.message
    if m is not None:
        m.exception_type = type(exc).__name__
        m.exception_module = type(exc).__module__


def _spool_path() -> str:
    d = os.path.join(paths.state_dir(), 'usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'messages.jsonl')


def _write_spool(message: UsageMessage) -> None:
    path = _spool_path()
    try:
        if (os.path.exists(path)
                and os.path.getsize(path) > _SPOOL_MAX_BYTES):
            os.replace(path, path + '.1')
        with open(path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(message.to_json()) + '\n')
    except OSError as e:
        logger.debug(f'usage spool write failed: {e}')


def _post(message: UsageMessage) -> None:
    endpoint = os.environ.get(_ENDPOINT_ENV)
    if not endpoint:
        return
    try:
        req = urllib.request.Request(
            endpoint,
            data=json.dumps(message.to_json()).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=_POST_TIMEOUT_SECONDS):
            pass
    except (urllib.error.URLError, OSError, ValueError) as e:
        logger.debug(f'usage post failed: {e}')


def _flush(message: UsageMessage) -> None:
    _write_spool(message)
    _post(message)


@contextlib.contextmanager
def entrypoint_context(name: str) -> Iterator[None]:
    """Re-entrant usage scope: outermost call owns + flushes the
    message; inner entrypoints only append to its api_calls trail."""
    if _disabled():
        yield
        return
    _state.depth += 1
    is_outermost = _state.depth == 1
    if is_outermost:
        _state.message = _new_message(name)
    else:
        m = _state.message
        if m is not None:
            m.api_calls.append(name)
    try:
        yield
        if is_outermost and _state.message is not None:
            _state.message.ok = True
    except (Exception, SystemExit, KeyboardInterrupt) as e:
        record_exception(e)
        if is_outermost and _state.message is not None:
            _state.message.ok = False
        raise
    finally:
        _state.depth -= 1
        if is_outermost:
            m = _state.message
            _state.message = None
            if m is not None:
                m.duration_seconds = round(time.time() - m.start_time, 3)
                _flush(m)


def entrypoint(name_or_fn):
    """Decorator form: @usage.entrypoint or @usage.entrypoint('name')."""
    if isinstance(name_or_fn, str):
        def named(fn):
            return _wrap(fn, name_or_fn)
        return named
    return _wrap(name_or_fn, name_or_fn.__qualname__)


def _wrap(fn, name: str):
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with entrypoint_context(name):
            return fn(*args, **kwargs)
    return wrapper


def read_spool() -> List[Dict[str, Any]]:
    """All spooled messages (newest last); for tests and `sky check`."""
    path = _spool_path()
    out = []
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    except OSError:
        pass
    return out
