"""Usage telemetry (reference: sky/usage/)."""
from skypilot_tpu.usage.usage_lib import (entrypoint, entrypoint_context,
                                          messages,
                                          record_cluster_name,
                                          record_exception,
                                          record_task)

__all__ = ['entrypoint', 'entrypoint_context', 'messages',
           'record_cluster_name', 'record_exception', 'record_task']
