"""GCP catalog: TPU slice offerings + host VM types, prices, zones.

Counterpart of the reference's sky/clouds/service_catalog/gcp_catalog.py
(:420-553 TPU row handling) and the hosted-CSV cache in
service_catalog/common.py:29-115.  Differences by design:
  - TPU offerings are *computed* from the generation topology table
    (utils/accelerator_registry.py) instead of enumerating thousands of
    CSV rows: any valid slice shape of a generation is priced as
    chips x price-per-chip-hour x region multiplier.
  - Prices/zones come from a built-in snapshot of public list prices
    (2025) overridable by `~/.skytpu/catalogs/v1/gcp/{vms,tpu_prices,
    tpu_zones}.csv` (written by `sky catalog update` — export the
    snapshot, edit, or fetch a hosted CSV; catalog/common.py), plus the
    in-process `set_pricing_override` hook.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions
from skypilot_tpu.utils import accelerator_registry

# ---------------------------------------------------------------------------
# TPU pricing: $ per chip-hour, on-demand and spot (public list prices,
# us-central anchors). v2/v3 are priced per-core by GCP; normalized to
# per-chip here (2 cores/chip).
# ---------------------------------------------------------------------------
_TPU_PRICE_PER_CHIP_HOUR: Dict[str, Tuple[float, float]] = {
    # gen: (on_demand, spot)
    'v2': (1.125, 0.3375),
    'v3': (2.00, 0.60),
    'v4': (3.22, 0.966),
    'v5e': (1.20, 0.48),
    'v5p': (4.20, 1.68),
    'v6e': (2.70, 1.08),
}

_REGION_PRICE_MULTIPLIER: Dict[str, float] = {
    'us-central1': 1.0,
    'us-central2': 1.0,
    'us-east1': 1.0,
    'us-east5': 1.0,
    'us-west1': 1.0,
    'us-west4': 1.05,
    'us-south1': 1.05,
    'europe-west4': 1.10,
    'asia-east1': 1.15,
    'asia-northeast1': 1.15,
}

# Zones where each TPU generation is available (public availability snapshot).
_TPU_ZONES: Dict[str, List[str]] = {
    'v2': ['us-central1-b', 'us-central1-c', 'us-central1-f',
           'europe-west4-a', 'asia-east1-c'],
    'v3': ['us-central1-a', 'us-central1-b', 'europe-west4-a'],
    'v4': ['us-central2-b'],
    'v5e': ['us-central1-a', 'us-west4-a', 'us-east1-c', 'us-east5-b',
            'europe-west4-b'],
    'v5p': ['us-east5-a', 'us-central1-a', 'europe-west4-b'],
    'v6e': ['us-east1-d', 'us-east5-a', 'us-east5-b', 'europe-west4-a',
            'asia-northeast1-b', 'us-south1-a'],
}

# Max chips of a single slice per generation (largest public pod slice).
_TPU_MAX_CHIPS: Dict[str, int] = {
    'v2': 256, 'v3': 512, 'v4': 4096, 'v5e': 256, 'v5p': 8960, 'v6e': 256,
}

# ---------------------------------------------------------------------------
# Host VM types (controllers, CPU-only tasks, GPU VMs). Small static table;
# per-region multiplier applies.  price = on-demand $/h, spot_price = $/h.
# ---------------------------------------------------------------------------
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
n2-standard-2,2,8,,0,0.0971,0.0233
n2-standard-4,4,16,,0,0.1942,0.0466
n2-standard-8,8,32,,0,0.3885,0.0932
n2-standard-16,16,64,,0,0.7769,0.1864
n2-standard-32,32,128,,0,1.5539,0.3729
n2-highmem-8,8,64,,0,0.5241,0.1258
e2-standard-2,2,8,,0,0.0670,0.0201
e2-standard-4,4,16,,0,0.1340,0.0402
e2-standard-8,8,32,,0,0.2681,0.0804
a2-highgpu-1g,12,85,A100,1,3.6730,1.1019
a2-highgpu-8g,96,680,A100,8,29.3838,8.8151
a2-ultragpu-8g,96,1360,A100-80GB,8,40.5500,12.1650
g2-standard-4,4,16,L4,1,0.7054,0.2116
g2-standard-48,48,192,L4,4,2.8216,0.8465
a3-highgpu-8g,208,1872,H100,8,88.2500,26.4750
"""

_VM_ZONES = ['us-central1-a', 'us-central1-b', 'us-central2-b', 'us-east1-c',
             'us-east5-a', 'us-east5-b', 'us-west1-a', 'us-west4-a',
             'europe-west4-a', 'europe-west4-b', 'asia-east1-c',
             'asia-northeast1-b', 'us-south1-a', 'us-east1-d',
             'us-central1-c', 'us-central1-f']

_df: Optional['pd.DataFrame'] = None
_tpu_price_table: Optional[Dict[str, Tuple[float, float]]] = None
_tpu_zone_table: Optional[Dict[str, List[str]]] = None
_pricing_override: Dict[str, Tuple[float, float]] = {}

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

# Date the in-code price tables above were snapshotted from public
# list prices; catalog/common.py warns when this rots without a
# fetched override in place.
SNAPSHOT_DATE = '2025-03-01'


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd  # deferred: keep `import skypilot_tpu` light

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('gcp', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('gcp', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def _tpu_prices() -> Dict[str, Tuple[float, float]]:
    global _tpu_price_table
    if _tpu_price_table is None:
        from skypilot_tpu.catalog import common
        table = dict(_TPU_PRICE_PER_CHIP_HOUR)
        df = common.read_catalog_csv('gcp', 'tpu_prices',
                                     ['generation', 'price',
                                      'spot_price'])
        if df is not None:
            for _, row in df.iterrows():
                table[str(row['generation'])] = (float(row['price']),
                                                 float(row['spot_price']))
        _tpu_price_table = table
    return _tpu_price_table


def _tpu_zone_map() -> Dict[str, List[str]]:
    global _tpu_zone_table
    if _tpu_zone_table is None:
        from skypilot_tpu.catalog import common
        df = common.read_catalog_csv('gcp', 'tpu_zones',
                                     ['generation', 'zone'])
        # MERGE over the snapshot (same semantics as tpu_prices): a
        # partial override replaces only the generations it lists.
        table = dict(_TPU_ZONES)
        if df is not None:
            overridden: Dict[str, List[str]] = {}
            for _, row in df.iterrows():
                overridden.setdefault(str(row['generation']), []).append(
                    str(row['zone']))
            table.update(overridden)
        _tpu_zone_table = table
    return _tpu_zone_table


def reload() -> None:
    """Drop memoized tables so on-disk overrides take effect (called
    after `sky catalog update` and by tests)."""
    global _df, _tpu_price_table, _tpu_zone_table
    _df = None
    _tpu_price_table = None
    _tpu_zone_table = None


def export_snapshot() -> Dict[str, str]:
    """The currently-effective tables as CSV text, keyed by table name
    (`sky catalog update --export` writes these to the cache dir as a
    starting point for hand edits)."""
    prices = _tpu_prices()
    price_lines = ['generation,price,spot_price'] + [
        f'{g},{od},{sp}' for g, (od, sp) in sorted(prices.items())]
    zone_lines = ['generation,zone'] + [
        f'{g},{z}' for g, zs in sorted(_tpu_zone_map().items())
        for z in zs]
    return {
        'vms': _vm_df().to_csv(index=False),
        'tpu_prices': '\n'.join(price_lines) + '\n',
        'tpu_zones': '\n'.join(zone_lines) + '\n',
    }


def set_pricing_override(per_chip: Dict[str, Tuple[float, float]]) -> None:
    _pricing_override.update(per_chip)


def zone_to_region(zone: str) -> str:
    return zone.rsplit('-', 1)[0]


def _region_multiplier(region: Optional[str]) -> float:
    if region is None:
        return 1.0
    return _REGION_PRICE_MULTIPLIER.get(region, 1.1)


# ---------------------------------------------------------------------------
# TPU offerings
# ---------------------------------------------------------------------------
def validate_tpu_slice(spec: accelerator_registry.TpuSliceSpec) -> None:
    gen = spec.generation.name
    max_chips = _TPU_MAX_CHIPS[gen]
    if spec.num_chips > max_chips:
        raise exceptions.ResourcesValidationError(
            f'{spec.accelerator_name}: {spec.num_chips} chips exceeds the '
            f'largest {gen} slice ({max_chips} chips).')
    if spec.num_chips > 1 and spec.num_chips % 2 != 0:
        raise exceptions.ResourcesValidationError(
            f'{spec.accelerator_name}: chip count must be even.')


def tpu_zones(gen: str, region: Optional[str] = None,
              zone: Optional[str] = None) -> List[str]:
    zones = _tpu_zone_map().get(gen, [])
    if region is not None:
        zones = [z for z in zones if zone_to_region(z) == region]
    if zone is not None:
        zones = [z for z in zones if z == zone]
    return zones


def tpu_regions(gen: str) -> List[str]:
    return sorted({zone_to_region(z) for z in _tpu_zone_map().get(gen, [])})


def get_tpu_hourly_cost(spec: accelerator_registry.TpuSliceSpec,
                        use_spot: bool,
                        region: Optional[str] = None,
                        zone: Optional[str] = None) -> float:
    gen = spec.generation.name
    if zone is not None and region is None:
        region = zone_to_region(zone)
    od, spot = _pricing_override.get(gen, _tpu_prices()[gen])
    per_chip = spot if use_spot else od
    return per_chip * spec.num_chips * _region_multiplier(region)


def tpu_supports_spot(gen: str) -> bool:
    return True  # All current generations offer preemptible/spot capacity.


# ---------------------------------------------------------------------------
# VM offerings
# ---------------------------------------------------------------------------
def instance_type_exists(instance_type: str) -> bool:
    if instance_type == 'TPU-VM':
        return True
    return instance_type in set(_vm_df()['instance_type'])


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    if instance_type == 'TPU-VM':
        # TPU-VM host cost is bundled into the accelerator price (same
        # modeling as the reference, sky/clouds/gcp.py:600-651).
        return 0.0
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesValidationError(
            f'Unknown GCP instance type {instance_type!r}.')
    if zone is not None and region is None:
        region = zone_to_region(zone)
    price = rows.iloc[0]['spot_price' if use_spot else 'price']
    return float(price) * _region_multiplier(region)


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    if instance_type == 'TPU-VM':
        return None, None
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        return None, None
    return float(rows.iloc[0]['vcpus']), float(rows.iloc[0]['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty or not isinstance(rows.iloc[0]['accelerator_name'], str):
        return None
    name = rows.iloc[0]['accelerator_name']
    if not name:
        return None
    return {name: int(rows.iloc[0]['accelerator_count'])}


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    """Cheapest VM meeting the cpu/memory request. '8+' means >= 8; plain
    '8' means exactly 8 (reference resources semantics)."""
    del disk_tier
    df = _vm_df()
    df = df[df['accelerator_count'] == 0]
    if cpus is None and memory is None:
        cpus = '8'

    import pandas as pd

    def _match(series: 'pd.Series', request: Optional[str]) -> 'pd.Series':
        if request is None:
            return pd.Series(True, index=series.index)
        if request.endswith('+'):
            return series >= float(request[:-1])
        if request.endswith('x'):  # memory = Nx vcpus form
            return pd.Series(True, index=series.index)
        return series == float(request)

    mask = _match(df['vcpus'], cpus) & _match(df['memory_gb'], memory)
    if memory is not None and memory.endswith('x'):
        factor = float(memory[:-1])
        mask &= df['memory_gb'] >= df['vcpus'] * factor
    candidates = df[mask].sort_values('price')
    if candidates.empty:
        return None
    return str(candidates.iloc[0]['instance_type'])


def get_instance_type_for_accelerator(
        acc_name: str, acc_count: int) -> Optional[List[str]]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name) &
              (df['accelerator_count'] == acc_count)]
    if rows.empty:
        return None
    return list(rows.sort_values('price')['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int, use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    if acc_name.lower().startswith('tpu-'):
        spec = accelerator_registry.parse_tpu_accelerator(acc_name, acc_count)
        return get_tpu_hourly_cost(spec, use_spot, region, zone)
    # GPU prices are bundled in their host instance types (a2/g2/a3).
    return 0.0


def vm_zones(region: Optional[str] = None,
             zone: Optional[str] = None) -> List[str]:
    zones = list(_VM_ZONES)
    if region is not None:
        zones = [z for z in zones if zone_to_region(z) == region]
    if zone is not None:
        zones = [z for z in zones if z == zone]
    return zones


def list_accelerators(
        name_filter: Optional[str] = None
) -> Dict[str, List[Dict[str, object]]]:
    """Inventory for `show-tpus` (reference: `sky show-gpus`,
    service_catalog.list_accelerators)."""
    out: Dict[str, List[Dict[str, object]]] = {}
    for gen_key, gen in accelerator_registry.TPU_GENERATIONS.items():
        base = 8 if not gen.counts_chips else 4
        sizes: List[int] = []
        n = base
        while True:
            spec = accelerator_registry.parse_tpu_accelerator(
                f'tpu-{gen_key}-{n}')
            if spec.num_chips > _TPU_MAX_CHIPS[gen_key]:
                break
            sizes.append(n)
            n *= 2
        for n in sizes:
            spec = accelerator_registry.parse_tpu_accelerator(
                f'tpu-{gen_key}-{n}')
            name = spec.accelerator_name
            if name_filter and name_filter.lower() not in name:
                continue
            out.setdefault(name, []).append({
                'accelerator_name': name,
                'chips': spec.num_chips,
                'hosts': spec.num_hosts,
                'hbm_gb': spec.total_hbm_gb,
                'bf16_tflops': spec.total_bf16_tflops,
                'price': get_tpu_hourly_cost(spec, False),
                'spot_price': get_tpu_hourly_cost(spec, True),
                'regions': tpu_regions(gen_key),
            })
    df = _vm_df()
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = f"{row['accelerator_name']}:{int(row['accelerator_count'])}"
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_name': row['accelerator_name'],
            'count': int(row['accelerator_count']),
            'instance_type': row['instance_type'],
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
