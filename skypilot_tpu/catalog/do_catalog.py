"""DigitalOcean catalog: droplet sizes, prices, regions.

Counterpart of the reference's service_catalog do tier.  DO prices
are flat per size across regions (no spot tier); GPU droplets
(gpu-h100x*) carry H100s.  Snapshot overridable by
`~/.skytpu/catalogs/v1/do/vms.csv`; refresh via `catalog update do`
(fetchers/fetch_do.py reads the public /v2/sizes API).
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# Public list prices 2025 ($/h; DO has no spot — spot mirrors price).
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
s-4vcpu-8gb,4,8,,0,0.0714,0.0714
s-8vcpu-16gb,8,16,,0,0.1429,0.1429
c-8,8,16,,0,0.25,0.25
c-16,16,32,,0,0.50,0.50
g-8vcpu-32gb,8,32,,0,0.3752,0.3752
m-8vcpu-64gb,8,64,,0,0.4988,0.4988
c-32,32,64,,0,1.00,1.00
gpu-h100x1-80gb,20,240,H100,1,3.39,3.39
gpu-h100x8-640gb,160,1920,H100,8,23.92,23.92
"""

_REGIONS = ['nyc1', 'nyc2', 'nyc3', 'sfo2', 'sfo3', 'ams3', 'fra1',
            'lon1', 'sgp1', 'blr1', 'syd1', 'tor1']
# GPU droplets exist only in these regions (public availability list).
_GPU_REGIONS = ['nyc2', 'tor1', 'ams3']

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('do', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('do', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions(instance_type: Optional[str] = None) -> List[str]:
    if instance_type and instance_type.startswith('gpu-'):
        return list(_GPU_REGIONS)
    return list(_REGIONS)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No DigitalOcean size {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    del use_spot, region, zone  # flat pricing, no spot tier
    return float(_row(instance_type)['price'])


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or \
            str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    return sorted(rows['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No DigitalOcean size offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone)
               for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
