"""FluidStack catalog: GPU plans, prices, regions.

Counterpart of the reference's service_catalog fluidstack tier (the
reference regenerates it with data_fetchers/fetch_fluidstack.py from
the public list_available_configurations API; ours refreshes via
`catalog update fluidstack` → fetchers/fetch_fluidstack.py).
Instance types keep the reference's `<GPU_TYPE>::<count>` grammar.
Snapshot overridable by `~/.skytpu/catalogs/v1/fluidstack/vms.csv`.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# Public per-GPU-hour list prices 2025 x count; no spot tier.
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
RTX_A6000_48GB::1,12,64,RTXA6000,1,0.49,0.49
RTX_A6000_48GB::2,24,128,RTXA6000,2,0.98,0.98
A100_PCIE_80GB::1,28,120,A100-80GB,1,1.49,1.49
A100_PCIE_80GB::2,56,240,A100-80GB,2,2.98,2.98
A100_PCIE_80GB::4,112,480,A100-80GB,4,5.96,5.96
A100_PCIE_80GB::8,224,960,A100-80GB,8,11.92,11.92
H100_PCIE_80GB::1,28,180,H100,1,2.89,2.89
H100_PCIE_80GB::2,56,360,H100,2,5.78,5.78
H100_PCIE_80GB::4,112,720,H100,4,11.56,11.56
H100_PCIE_80GB::8,224,1440,H100,8,23.12,23.12
"""

_REGIONS = ['norway_2_eu', 'canada_1_ca', 'iceland_1_eu',
            'united_states_1_us']

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('fluidstack', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('fluidstack', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions() -> List[str]:
    return list(_REGIONS)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No FluidStack plan {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    del use_spot, region, zone  # flat pricing, no spot tier
    return float(_row(instance_type)['price'])


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or \
            str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    # GPU-only platform: default to the cheapest qualifying plan.
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory,
                                             allow_accelerators=True)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    return sorted(rows['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No FluidStack plan offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone)
               for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
