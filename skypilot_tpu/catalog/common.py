"""Catalog cache: on-disk CSV overrides + update machinery.

Reference: sky/clouds/service_catalog/common.py:29-115 — the hosted-CSV
fetch + `~/.sky/catalogs/v<N>/` cache with lazily-loaded dataframes.
Here the tiering is:

    1. in-code snapshot (always present; ships with the package),
    2. `~/.skytpu/catalogs/v1/<cloud>/<table>.csv` override when it
       exists — written by `sky catalog update`, which can export the
       built-in snapshot for hand-editing, import a file, or fetch a
       URL (a hosted catalog or a pricing-API exporter's output).

So deployments refresh prices/zones without code edits, and air-gapped
environments keep working off the snapshot.
"""
from __future__ import annotations

import os
import typing
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import paths

if typing.TYPE_CHECKING:
    import pandas as pd

logger = sky_logging.init_logger(__name__)

CATALOG_SCHEMA_VERSION = 'v1'


def catalog_dir(cloud: str) -> str:
    return os.path.join(paths.catalogs_dir(), CATALOG_SCHEMA_VERSION,
                        cloud)


def catalog_path(cloud: str, table: str) -> str:
    return os.path.join(catalog_dir(cloud), f'{table}.csv')


def read_catalog_csv(cloud: str, table: str,
                     required_columns: Optional[List[str]] = None
                     ) -> Optional['pd.DataFrame']:
    """The on-disk override for a table, or None to use the snapshot."""
    path = catalog_path(cloud, table)
    if not os.path.exists(path):
        return None
    import pandas as pd
    try:
        df = pd.read_csv(path)
    except Exception as e:  # noqa: BLE001 — corrupt override
        logger.warning(f'Ignoring unreadable catalog override {path}: '
                       f'{e}')
        return None
    missing = set(required_columns or []) - set(df.columns)
    if missing:
        logger.warning(
            f'Ignoring catalog override {path}: missing columns '
            f'{sorted(missing)}')
        return None
    return df


def write_catalog_csv(cloud: str, table: str, text: str) -> str:
    path = catalog_path(cloud, table)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f'.tmp{os.getpid()}'
    with open(tmp, 'w', encoding='utf-8') as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def update_from_file(cloud: str, table: str, source_path: str) -> str:
    with open(os.path.expanduser(source_path), encoding='utf-8') as f:
        return write_catalog_csv(cloud, table, f.read())


def update_from_url(cloud: str, table: str, url: str,
                    timeout: float = 30.0) -> str:
    """Fetch a hosted catalog CSV (reference: hosted-catalog HTTP fetch,
    service_catalog/common.py:159)."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            text = resp.read().decode('utf-8')
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise exceptions.SkyTpuError(
            f'Could not fetch catalog {table} from {url}: {e}. '
            'Offline? Use `sky catalog update --from-file` or keep the '
            'built-in snapshot.') from e
    return write_catalog_csv(cloud, table, text)


def parse_bound(request) -> 'tuple[Optional[float], bool]':
    """Resource-request grammar shared by the VM catalogs:
    '8+' -> (8.0, True: at-least), '8' -> (8.0, False: exact),
    None -> (None, False)."""
    if request is None:
        return None, False
    s = str(request)
    if s.endswith('+'):
        return float(s[:-1]), True
    return float(s), False


VMS_CSV_HEADER = ('instance_type,vcpus,memory_gb,accelerator_name,'
                  'accelerator_count,price,spot_price')


def rows_to_vms_csv(rows) -> str:
    """Serialize fetcher row dicts into the shared vms-table CSV —
    ONE copy of the column order every per-cloud catalog reads."""
    lines = [VMS_CSV_HEADER]
    for r in rows:
        lines.append(f"{r['instance_type']},{r['vcpus']},"
                     f"{r['memory_gb']},{r['accelerator_name']},"
                     f"{r['accelerator_count']},{r['price']},"
                     f"{r['spot_price']}")
    return '\n'.join(lines) + '\n'


def pick_default_instance_type(df, cpus: Optional[str],
                               memory: Optional[str],
                               min_default_vcpus: int = 8,
                               allow_accelerators: bool = False
                               ) -> Optional[str]:
    """Cheapest CPU-only row of a vms dataframe satisfying the
    cpus/memory request — ONE copy of the selection the per-cloud
    catalogs share, including the implicit >=8-vCPU floor when nothing
    is requested.  GPU-only clouds (RunPod) pass allow_accelerators to
    default to their cheapest qualifying GPU pod instead of nothing."""
    if not allow_accelerators:
        df = df[df['accelerator_count'] == 0]
    cpu_val, cpu_plus = parse_bound(cpus)
    mem_val, mem_plus = parse_bound(memory)
    if cpu_val is not None:
        df = df[df['vcpus'] >= cpu_val] if cpu_plus else \
            df[df['vcpus'] == cpu_val]
    elif memory is None:
        df = df[df['vcpus'] >= min_default_vcpus]
    if mem_val is not None:
        df = df[df['memory_gb'] >= mem_val] if mem_plus else \
            df[df['memory_gb'] == mem_val]
    if df.empty:
        return None
    return str(df.sort_values('price').iloc[0]['instance_type'])


SNAPSHOT_MAX_AGE_DAYS = 180
_stale_warned: set = set()


def warn_if_snapshot_stale(cloud: str, snapshot_date: str,
                           table: str = 'vms') -> None:
    """Once per process: flag a built-in price snapshot past its
    shelf life when no fetched/imported override is in effect —
    prices silently rot otherwise (the r2 verdict's catalog gap)."""
    if cloud in _stale_warned or os.path.exists(
            catalog_path(cloud, table)):
        return
    import datetime
    try:
        age = (datetime.date.today()
               - datetime.date.fromisoformat(snapshot_date)).days
    except ValueError:
        return
    if age > SNAPSHOT_MAX_AGE_DAYS:
        _stale_warned.add(cloud)
        # Only clouds with a pricing-API fetcher can honor --fetch;
        # the rest take --from-file / --export edits.
        from skypilot_tpu.catalog import fetchers
        fetchable = cloud in fetchers.FETCHABLE
        hint = (f'Refresh with: sky catalog update --cloud {cloud} '
                '--fetch' if fetchable else
                f'Override with: sky catalog update --cloud {cloud} '
                '--table vms --from-file <csv>')
        logger.warning(
            f'{cloud} catalog is the built-in snapshot from '
            f'{snapshot_date} ({age} days old); prices may be stale. '
            + hint)


def remove_override(cloud: str, table: str) -> bool:
    path = catalog_path(cloud, table)
    try:
        os.unlink(path)
        return True
    except FileNotFoundError:
        return False
