"""Samsung Cloud Platform catalog (reference service_catalog scp
tier).  Standard/High-memory CPU servers + T4/V100 GPU servers; flat
hourly pricing, no spot."""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
s1v2m4,2,4,,0,0.059,0.059
s1v8m16,8,16,,0,0.236,0.236
s1v16m32,16,32,,0,0.472,0.472
h1v8m64,8,64,,0,0.355,0.355
g1v8m32t4,8,32,T4,1,0.756,0.756
g1v16m64t4,16,64,T4,2,1.512,1.512
g1v8m64v100,8,64,V100,1,2.10,2.10
g1v32m256v100,32,256,V100,4,8.40,8.40
"""

CATALOG = flat.FlatCatalog(
    'scp', _VMS_CSV,
    regions=['KR-WEST-1', 'KR-EAST-1', 'KR-WEST-2'],
    snapshot_date='2025-03-01', display_name='SCP')
