"""Paperspace catalog (reference service_catalog paperspace tier).

Machine types are Paperspace's own names (C-series CPU, GPU+ /
A4000-A100 GPU machines); flat hourly pricing, no spot.
"""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
C5,4,16,,0,0.08,0.08
C7,12,30,,0,0.30,0.30
P4000,8,30,P4000,1,0.51,0.51
RTX4000,8,30,RTX4000,1,0.56,0.56
A4000,8,45,RTXA4000,1,0.76,0.76
A4000x2,16,90,RTXA4000,2,1.52,1.52
A100,12,90,A100,1,3.09,3.09
A100-80Gx8,96,640,A100-80GB,8,25.44,25.44
H100,20,250,H100,1,5.95,5.95
H100x8,128,1600,H100,8,47.60,47.60
"""

CATALOG = flat.FlatCatalog(
    'paperspace', _VMS_CSV,
    regions=['East Coast (NY2)', 'West Coast (CA1)', 'Europe (AMS1)'],
    snapshot_date='2025-03-01', display_name='Paperspace')
