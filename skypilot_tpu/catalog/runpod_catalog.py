"""RunPod catalog: GPU pod types, on-demand + spot (interruptible)
prices.

Counterpart of the reference's service_catalog runpod tier.  RunPod
prices per-GPU and sells SECURE (datacenter) and COMMUNITY (hosted)
tiers; instance types keep the reference's `<n>x_<GPU>_<TIER>` shape
so recipes port verbatim.  Region = country code (capacity is
placement-matched, not zonal).  Snapshot overridable by
`~/.skytpu/catalogs/v1/runpod/vms.csv`; refresh via
`catalog update runpod` (fetchers/fetch_runpod.py).
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# Public list prices 2025 ($/h per pod: per-GPU price x count; spot =
# interruptible market floor).
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
1x_RTX4090_SECURE,8,32,RTX4090,1,0.69,0.35
1x_A40_SECURE,8,48,A40,1,0.39,0.20
1x_L40S_SECURE,12,48,L40S,1,0.99,0.50
1x_A100-80GB_SECURE,12,96,A100-80GB,1,1.64,0.82
2x_A100-80GB_SECURE,24,192,A100-80GB,2,3.28,1.64
4x_A100-80GB_SECURE,48,384,A100-80GB,4,6.56,3.28
8x_A100-80GB_SECURE,96,768,A100-80GB,8,13.12,6.56
1x_H100_SECURE,16,96,H100,1,2.99,1.50
2x_H100_SECURE,32,192,H100,2,5.98,3.00
4x_H100_SECURE,64,384,H100,4,11.96,6.00
8x_H100-SXM_SECURE,128,768,H100-SXM,8,35.92,18.00
1x_RTX4090_COMMUNITY,8,32,RTX4090,1,0.44,0.22
1x_A100-80GB_COMMUNITY,12,96,A100-80GB,1,1.19,0.60
"""

_REGIONS = ['US', 'CA', 'NL', 'NO', 'RO', 'SE', 'IS']

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('runpod', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('runpod', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions() -> List[str]:
    return list(_REGIONS)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No RunPod instance type {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    del region, zone  # flat per-type pricing
    row = _row(instance_type)
    return float(row['spot_price'] if use_spot else row['price'])


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or \
            str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    # Every RunPod pod carries a GPU; the cheapest qualifying pod is
    # the default (no CPU-only tier to prefer).
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory,
                                             allow_accelerators=True)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    # SECURE before COMMUNITY at equal spec: sort by price then name.
    return list(rows.sort_values(['price', 'instance_type'])
                ['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No RunPod instance type offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone)
               for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
