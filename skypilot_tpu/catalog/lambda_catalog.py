"""Lambda Cloud catalog: GPU instance types, prices, regions.

Counterpart of the reference's
sky/clouds/service_catalog/lambda_catalog.py — the minor-cloud tier.
Lambda sells flat-rate GPU boxes (no spot, no stop): one price per
type, identical across regions, so no multiplier table.  Snapshot
overridable by `~/.skytpu/catalogs/v1/lambda/vms.csv`.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# Public list prices 2025 ($/h, flat — Lambda has no spot tier;
# spot_price mirrors price so shared cost plumbing stays total).
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
gpu_1x_a10,30,200,A10,1,0.75,0.75
gpu_1x_a100_sxm4,30,200,A100,1,1.29,1.29
gpu_8x_a100_80gb_sxm4,240,1800,A100-80GB,8,14.32,14.32
gpu_1x_h100_pcie,26,200,H100,1,2.49,2.49
gpu_8x_h100_sxm5,208,1800,H100,8,23.92,23.92
cpu_4x_general,4,16,,0,0.08,0.08
"""

_REGIONS = ['us-east-1', 'us-west-1', 'us-west-2', 'us-midwest-1',
            'europe-central-1', 'asia-south-1']

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('lambda', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('lambda', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions() -> List[str]:
    return list(_REGIONS)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No Lambda instance type {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    del use_spot, region, zone  # flat pricing, no spot tier
    return float(_row(instance_type)['price'])


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    return sorted(rows['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No Lambda instance type offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone)
               for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
