"""AWS EC2 catalog: instance types, prices, regions/AZs.

Counterpart of the reference's sky/clouds/service_catalog/aws_catalog.py
(hosted-CSV cache + az-mapping; reference common.py:29-115).  Same
structure as catalog/gcp_catalog.py: a built-in snapshot of public
on-demand/spot list prices (us-east-1 anchors, per-region multiplier),
overridable by `~/.skytpu/catalogs/v1/aws/vms.csv` (written/edited via
`sky catalog update`; catalog/common.py).

The AWS story here is deliberately VM-only (no TPUs on AWS): it gives
the optimizer true multi-cloud placement — CPU controllers, GPU
fallbacks, and egress-priced cross-cloud DAG stages — against the
TPU-first GCP path.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# price/spot_price are us-east-1 anchors ($/h, public list 2025).
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
t3.medium,2,4,,0,0.0416,0.0125
m6i.large,2,8,,0,0.0960,0.0288
m6i.xlarge,4,16,,0,0.1920,0.0576
m6i.2xlarge,8,32,,0,0.3840,0.1152
m6i.4xlarge,16,64,,0,0.7680,0.2304
m6i.8xlarge,32,128,,0,1.5360,0.4608
c6i.4xlarge,16,32,,0,0.6800,0.2040
r6i.2xlarge,8,64,,0,0.5040,0.1512
g5.xlarge,4,16,A10G,1,1.0060,0.3018
g5.12xlarge,48,192,A10G,4,5.6720,1.7016
p4d.24xlarge,96,1152,A100,8,32.7726,9.8318
p4de.24xlarge,96,1152,A100-80GB,8,40.9657,12.2897
p5.48xlarge,192,2048,H100,8,98.3200,29.4960
"""

_REGION_PRICE_MULTIPLIER: Dict[str, float] = {
    'us-east-1': 1.0,
    'us-east-2': 1.0,
    'us-west-2': 1.0,
    'eu-west-1': 1.10,
    'eu-central-1': 1.15,
    'ap-northeast-1': 1.20,
}

# Availability zones per region (suffix letters; snapshot of typical AZ
# sets — the provisioner treats any listed AZ as a candidate).
_REGION_AZS: Dict[str, List[str]] = {
    'us-east-1': ['a', 'b', 'c', 'd', 'f'],
    'us-east-2': ['a', 'b', 'c'],
    'us-west-2': ['a', 'b', 'c', 'd'],
    'eu-west-1': ['a', 'b', 'c'],
    'eu-central-1': ['a', 'b', 'c'],
    'ap-northeast-1': ['a', 'c', 'd'],
}

# GPU instance types are not offered everywhere; snapshot of regions
# with P4/P5/G5 capacity pools.
_GPU_REGIONS = ['us-east-1', 'us-east-2', 'us-west-2', 'eu-west-1',
                'eu-central-1', 'ap-northeast-1']

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

# See gcp_catalog.SNAPSHOT_DATE — same staleness contract.
SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd  # deferred: keep `import skypilot_tpu` light

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('aws', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('aws', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions() -> List[str]:
    return sorted(_REGION_AZS)


def zones(region: Optional[str] = None,
          zone: Optional[str] = None) -> List[str]:
    out = []
    for r, suffixes in sorted(_REGION_AZS.items()):
        if region is not None and r != region:
            continue
        for s in suffixes:
            z = f'{r}{s}'
            if zone is None or z == zone:
                out.append(z)
    return out


def zone_to_region(zone: str) -> str:
    # 'us-east-1a' -> 'us-east-1'
    return zone.rstrip('abcdef')


def _region_multiplier(region: Optional[str]) -> float:
    if region is None:
        return 1.0
    return _REGION_PRICE_MULTIPLIER.get(region, 1.2)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No AWS instance type {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    if zone is not None and region is None:
        region = zone_to_region(zone)
    row = _row(instance_type)
    base = float(row['spot_price'] if use_spot else row['price'])
    return base * _region_multiplier(region)


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def _parse_bound(request: Optional[str]) -> Tuple[Optional[float], bool]:
    from skypilot_tpu.catalog import common
    return common.parse_bound(request)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    return sorted(rows['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No AWS instance type offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone) for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    """name -> offerings (for `sky show-accelerators`)."""
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
