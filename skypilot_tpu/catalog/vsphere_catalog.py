"""vSphere catalog (reference service_catalog vsphere tier).

On-prem vCenter: "instance types" are VM shape presets and the
"price" is an internal chargeback anchor (the reference fetches real
inventory with fetch_vsphere.py; here the standard preset table can
be overridden per site via the catalog cache —
~/.skytpu/catalogs/v1/vsphere/vms.csv).  Regions = datacenter names.
"""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
cpu-small,4,16,,0,0.05,0.05
cpu-medium,8,32,,0,0.10,0.10
cpu-large,16,64,,0,0.20,0.20
gpu-t4-8x32,8,32,T4,1,0.40,0.40
gpu-v100-8x64,8,64,V100,1,1.20,1.20
gpu-a100-16x128,16,128,A100,1,2.40,2.40
"""

CATALOG = flat.FlatCatalog(
    'vsphere', _VMS_CSV,
    regions=['Datacenter'],
    snapshot_date='2025-03-01', display_name='vSphere')
