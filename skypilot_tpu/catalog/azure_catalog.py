"""Azure VM catalog: instance types, prices, regions/zones.

Counterpart of the reference's
sky/clouds/service_catalog/azure_catalog.py; same structure as
catalog/aws_catalog.py: a built-in snapshot of public pay-as-you-go /
spot list prices (eastus anchors, per-region multiplier), overridable
by `~/.skytpu/catalogs/v1/azure/vms.csv` (`sky catalog update`).

Azure zones are numbered (1/2/3) within a region; this catalog
represents them as '<region>-<n>'.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

# price/spot_price are eastus anchors ($/h, public list 2025).
_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
Standard_D2s_v5,2,8,,0,0.0960,0.0288
Standard_D4s_v5,4,16,,0,0.1920,0.0576
Standard_D8s_v5,8,32,,0,0.3840,0.1152
Standard_D16s_v5,16,64,,0,0.7680,0.2304
Standard_D32s_v5,32,128,,0,1.5360,0.4608
Standard_E8s_v5,8,64,,0,0.5040,0.1512
Standard_F16s_v2,16,32,,0,0.6770,0.2031
Standard_NC4as_T4_v3,4,28,T4,1,0.5260,0.1578
Standard_NC64as_T4_v3,64,440,T4,4,4.3520,1.3056
Standard_NV36ads_A10_v5,36,440,A10,1,3.2000,0.9600
Standard_NC24ads_A100_v4,24,220,A100-80GB,1,3.6730,1.1019
Standard_ND96asr_v4,96,900,A100,8,27.1970,8.1591
Standard_ND96amsr_A100_v4,96,1900,A100-80GB,8,32.7700,9.8310
Standard_NC40ads_H100_v5,40,320,H100,1,6.9800,2.0940
Standard_ND96isr_H100_v5,96,1900,H100,8,98.3200,29.4960
"""

_REGION_PRICE_MULTIPLIER: Dict[str, float] = {
    'eastus': 1.0,
    'eastus2': 1.0,
    'southcentralus': 1.05,
    'westus2': 1.0,
    'westeurope': 1.15,
    'northeurope': 1.10,
    'japaneast': 1.20,
}

# Azure availability zones are numbered per region.
_REGION_ZONES: Dict[str, List[str]] = {
    'eastus': ['1', '2', '3'],
    'eastus2': ['1', '2', '3'],
    'southcentralus': ['1', '2', '3'],
    'westus2': ['1', '2', '3'],
    'westeurope': ['1', '2', '3'],
    'northeurope': ['1', '2', '3'],
    'japaneast': ['1', '2', '3'],
}

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']

# See gcp_catalog.SNAPSHOT_DATE — same staleness contract.
SNAPSHOT_DATE = '2025-03-01'

_df: Optional['pd.DataFrame'] = None


def _vm_df() -> 'pd.DataFrame':
    global _df
    if _df is None:
        import pandas as pd  # deferred: keep `import skypilot_tpu` light

        from skypilot_tpu.catalog import common
        _df = common.read_catalog_csv('azure', 'vms', _VM_COLUMNS)
        if _df is None:
            common.warn_if_snapshot_stale('azure', SNAPSHOT_DATE)
            _df = pd.read_csv(io.StringIO(_VMS_CSV))
    return _df


def reload() -> None:
    global _df
    _df = None


def export_snapshot() -> Dict[str, str]:
    return {'vms': _vm_df().to_csv(index=False)}


def regions() -> List[str]:
    return sorted(_REGION_ZONES)


def zones(region: Optional[str] = None,
          zone: Optional[str] = None) -> List[str]:
    out = []
    for r, numbers in sorted(_REGION_ZONES.items()):
        if region is not None and r != region:
            continue
        for n in numbers:
            z = f'{r}-{n}'
            if zone is None or z == zone:
                out.append(z)
    return out


def zone_to_region(zone: str) -> str:
    # 'eastus-1' -> 'eastus'
    return zone.rsplit('-', 1)[0]


def zone_number(zone: str) -> str:
    # 'eastus-1' -> '1' (the ARM `zones` field value)
    return zone.rsplit('-', 1)[1]


def _region_multiplier(region: Optional[str]) -> float:
    if region is None:
        return 1.0
    return _REGION_PRICE_MULTIPLIER.get(region, 1.2)


def instance_type_exists(instance_type: str) -> bool:
    df = _vm_df()
    return bool((df['instance_type'] == instance_type).any())


def _row(instance_type: str):
    df = _vm_df()
    rows = df[df['instance_type'] == instance_type]
    if rows.empty:
        raise exceptions.ResourcesUnavailableError(
            f'No Azure instance type {instance_type!r}; have '
            f'{sorted(df["instance_type"])}')
    return rows.iloc[0]


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    if zone is not None and region is None:
        region = zone_to_region(zone)
    row = _row(instance_type)
    base = float(row['spot_price'] if use_spot else row['price'])
    return base * _region_multiplier(region)


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    row = _row(instance_type)
    return float(row['vcpus']), float(row['memory_gb'])


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    row = _row(instance_type)
    if not row['accelerator_name'] or str(row['accelerator_name']) == 'nan':
        return None
    return {str(row['accelerator_name']): int(row['accelerator_count'])}


def _parse_bound(request: Optional[str]) -> Tuple[Optional[float], bool]:
    from skypilot_tpu.catalog import common
    return common.parse_bound(request)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None,
                              disk_tier: Optional[str] = None
                              ) -> Optional[str]:
    del disk_tier
    from skypilot_tpu.catalog import common
    return common.pick_default_instance_type(_vm_df(), cpus, memory)


def get_instance_type_for_accelerator(acc_name: str,
                                      acc_count: int) -> List[str]:
    df = _vm_df()
    rows = df[(df['accelerator_name'] == acc_name)
              & (df['accelerator_count'] == acc_count)]
    return sorted(rows['instance_type'])


def get_accelerator_hourly_cost(acc_name: str, acc_count: int,
                                use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    types = get_instance_type_for_accelerator(acc_name, acc_count)
    if not types:
        raise exceptions.ResourcesUnavailableError(
            f'No Azure instance type offers {acc_name}:{acc_count}.')
    return min(get_hourly_cost(t, use_spot, region, zone) for t in types)


def list_accelerators(name_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, object]]]:
    """name -> offerings (for `sky show-accelerators`)."""
    df = _vm_df()
    out: Dict[str, List[Dict[str, object]]] = {}
    for _, row in df[df['accelerator_count'] > 0].iterrows():
        name = str(row['accelerator_name'])
        if name_filter and name_filter.lower() not in name.lower():
            continue
        out.setdefault(name, []).append({
            'accelerator_count': int(row['accelerator_count']),
            'instance_type': str(row['instance_type']),
            'price': float(row['price']),
            'spot_price': float(row['spot_price']),
        })
    return out
