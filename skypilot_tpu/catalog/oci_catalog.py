"""OCI catalog (reference service_catalog oci tier).

Flexible E4/E5 CPU shapes (fixed popular sizes snapshotted) + GPU
shapes (A10 / A100 / H100).  OCI has preemptible capacity at a flat
50% discount — has_spot with spot_price = price/2.
"""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
VM.Standard.E4.Flex-8-32,8,32,,0,0.20,0.10
VM.Standard.E4.Flex-16-64,16,64,,0,0.40,0.20
VM.Standard.E5.Flex-8-32,8,32,,0,0.24,0.12
VM.GPU.A10.1,15,240,A10,1,2.00,1.00
VM.GPU.A10.2,30,480,A10,2,4.00,2.00
BM.GPU.A100-v2.8,128,2048,A100-80GB,8,32.00,16.00
BM.GPU.H100.8,112,2048,H100,8,80.00,40.00
"""

CATALOG = flat.FlatCatalog(
    'oci', _VMS_CSV,
    regions=['us-ashburn-1', 'us-phoenix-1', 'eu-frankfurt-1',
             'uk-london-1', 'ap-tokyo-1', 'ap-mumbai-1'],
    snapshot_date='2025-03-01', has_spot=True, display_name='OCI')
