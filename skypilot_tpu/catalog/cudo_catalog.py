"""Cudo Compute catalog (reference service_catalog cudo tier).

Instance-type grammar keeps the reference's
`<machine_type>_<gpu>x<vcpu>v<mem>gb` (fetch_cudo.py:43-46) so specs
decompose back into the VM-create API's fields.
"""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
epyc-milan_0x8v32gb,8,32,,0,0.12,0.12
epyc-milan_0x16v64gb,16,64,,0,0.24,0.24
epyc-milan-rtx-a4000_1x4v16gb,4,16,RTXA4000,1,0.35,0.35
epyc-milan-rtx-a5000_1x8v32gb,8,32,RTXA5000,1,0.55,0.55
epyc-milan-rtx-a6000_1x8v48gb,8,48,RTXA6000,1,0.85,0.85
epyc-milan-rtx-a6000_4x32v192gb,32,192,RTXA6000,4,3.40,3.40
sapphire-rapids-h100_1x24v96gb,24,96,H100,1,2.79,2.79
sapphire-rapids-h100_8x192v768gb,192,768,H100,8,22.32,22.32
"""

CATALOG = flat.FlatCatalog(
    'cudo', _VMS_CSV,
    regions=['no-luster-1', 'se-smedjebacken-1', 'gb-london-1',
             'us-newyork-1', 'au-melbourne-1'],
    snapshot_date='2025-03-01', display_name='Cudo')
