"""Regenerate the FluidStack `vms` table from the public plans API.

Reference: sky/clouds/service_catalog/data_fetchers/
fetch_fluidstack.py — rebuilt against the same endpoint:

    GET https://platform.fluidstack.io/list_available_configurations
    (api-key header; returns plans with gpu_type, price_per_gpu_hr,
    gpu_counts, regions)

`fetch_json` is injectable for air-gapped tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

PATH = '/list_available_configurations'

# FluidStack gpu_type -> canonical accelerator name (subset the
# snapshot carries; unknown types pass through verbatim).
_GPU_NAMES = {
    'RTX_A6000_48GB': 'RTXA6000',
    'A100_PCIE_80GB': 'A100-80GB',
    'A100_SXM4_80GB': 'A100-80GB-SXM',
    'H100_PCIE_80GB': 'H100',
    'H100_SXM5_80GB': 'H100-SXM',
    'L40_48GB': 'L40',
}
# Host shape per GPU (vcpus, mem GB) — the plans API prices GPUs, not
# host shapes; these are FluidStack's published per-GPU allotments.
_PER_GPU_SHAPE = {'default': (28, 120)}


def _default_fetch_json(_path: str) -> List[Dict[str, Any]]:
    from skypilot_tpu.provision.fluidstack import fluidstack_api
    return fluidstack_api.request('GET', PATH)


def rows_from_plans(plans: List[Dict[str, Any]]):
    rows = []
    for plan in plans or []:
        gpu_type = str(plan.get('gpu_type', ''))
        if not gpu_type:
            continue
        per_gpu = float(plan.get('price_per_gpu_hr', 0) or 0)
        if per_gpu <= 0:
            continue
        acc = _GPU_NAMES.get(gpu_type, gpu_type)
        vcpus_per, mem_per = _PER_GPU_SHAPE['default']
        for count in sorted(set(plan.get('gpu_counts') or [1])):
            count = int(count)
            rows.append({
                'instance_type': f'{gpu_type}::{count}',
                'vcpus': vcpus_per * count,
                'memory_gb': mem_per * count,
                'accelerator_name': acc,
                'accelerator_count': count,
                'price': round(per_gpu * count, 4),
                'spot_price': round(per_gpu * count, 4),
            })
    return sorted(rows, key=lambda r: r['instance_type'])


def fetch_and_write(fetch_json: Optional[Callable[[str], Any]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import fluidstack_catalog
    fetch_json = fetch_json or _default_fetch_json
    rows = rows_from_plans(fetch_json(PATH))
    if not rows:
        raise RuntimeError('FluidStack plans API returned no plans; '
                           'keeping the previous table.')
    path = common.write_catalog_csv('fluidstack', 'vms',
                                    common.rows_to_vms_csv(rows))
    fluidstack_catalog.reload()
    return {'vms': path}
