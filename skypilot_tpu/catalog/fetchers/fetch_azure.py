"""Regenerate the Azure `vms` table from the Retail Prices API.

Reference: sky/clouds/service_catalog/data_fetchers/fetch_azure.py —
rebuilt against the unauthenticated Retail Prices endpoint (the one
public pricing API that needs no key and carries SPOT prices too):

    GET https://prices.azure.com/api/retail/prices?
        $filter=serviceName eq 'Virtual Machines'
                and armRegionName eq '<region>'
        (paginated via NextPageLink)

`fetch_json` is injectable for air-gapped tests.
"""
from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Iterator, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

RETAIL_URL = 'https://prices.azure.com/api/retail/prices'
BASE_REGION = 'eastus'


def _default_fetch_json(url: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return json.loads(resp.read())


def iter_items(region: str,
               fetch_json: Callable[[str], Dict[str, Any]]
               ) -> Iterator[Dict[str, Any]]:
    flt = (f"serviceName eq 'Virtual Machines' and "
           f"armRegionName eq '{region}'")
    url = RETAIL_URL + '?' + urllib.parse.urlencode({'$filter': flt})
    while url:
        page = fetch_json(url)
        yield from page.get('Items', [])
        url = page.get('NextPageLink') or ''


def collect_prices(items: Iterator[Dict[str, Any]],
                   wanted: set) -> Dict[str, Dict[str, float]]:
    """armSkuName -> {'od': $/h, 'spot': $/h} (Linux consumption)."""
    prices: Dict[str, Dict[str, float]] = {}
    for item in items:
        sku = item.get('armSkuName')
        if sku not in wanted:
            continue
        if item.get('type') != 'Consumption':
            continue
        product = item.get('productName', '')
        sku_name = item.get('skuName', '')
        if 'Windows' in product or 'Low Priority' in sku_name:
            continue
        price = float(item.get('retailPrice', 0) or 0)
        if price <= 0:
            continue
        kind = 'spot' if 'Spot' in sku_name else 'od'
        prices.setdefault(sku, {}).setdefault(kind, price)
    return prices


def fetch_and_write(region: str = BASE_REGION,
                    fetch_json: Optional[Callable[[str],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import azure_catalog
    from skypilot_tpu.catalog import common
    fetch_json = fetch_json or _default_fetch_json
    shapes = azure_catalog._vm_df()  # pylint: disable=protected-access
    wanted = set(shapes['instance_type'])
    prices = collect_prices(iter_items(region, fetch_json), wanted)
    # The vms table stores BASE_REGION anchors with a per-region
    # multiplier on top; prices fetched from another region must be
    # normalized back to the anchor or the multiplier double-counts.
    divisor = azure_catalog._REGION_PRICE_MULTIPLIER.get(region, 1.2)  # pylint: disable=protected-access
    if divisor != 1.0:
        logger.info(f'Normalizing {region} prices to '
                    f'{BASE_REGION} anchors (/{divisor}).')
        prices = {sku: {k: v / divisor for k, v in p.items()}
                  for sku, p in prices.items()}

    lines = ['instance_type,vcpus,memory_gb,accelerator_name,'
             'accelerator_count,price,spot_price']
    skipped = []
    for _, row in shapes.iterrows():
        itype = str(row['instance_type'])
        cur_od, cur_sp = float(row['price']), float(row['spot_price'])
        fresh = prices.get(itype, {})
        od = fresh.get('od')
        if od is None:
            od, sp = cur_od, cur_sp
            skipped.append(itype)
        else:
            # Retail API carries spot; fall back to the previous
            # spot/OD ratio only when the spot row is absent.
            sp = fresh.get('spot')
            if sp is None:
                sp = round(od * (cur_sp / cur_od), 4)
        acc = '' if not isinstance(row['accelerator_name'], str) \
            else row['accelerator_name']
        lines.append(f'{itype},{row["vcpus"]},{row["memory_gb"]},'
                     f'{acc},{int(row["accelerator_count"] or 0)},'
                     f'{od},{sp}')
    if skipped:
        logger.warning(
            f'No fresh Azure price for {skipped} (kept previous).')
    path = common.write_catalog_csv('azure', 'vms',
                                    '\n'.join(lines) + '\n')
    azure_catalog.reload()
    return {'vms': path}
