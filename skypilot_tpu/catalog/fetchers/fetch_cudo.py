"""Regenerate the Cudo `vms` table from the machine-types API.

Reference: sky/clouds/service_catalog/data_fetchers/fetch_cudo.py —
it walks a fixed (gpu, vcpu, mem) spec ladder and prices each
machine type from the API's per-unit rates.  Same approach here over
the REST endpoint the provisioner already uses:

    GET /v1/vms/machine-types  (Bearer key)
    -> machineTypes: [{machineType, gpuModel, dataCenterId,
                       gpuPriceHr, vcpuPriceHr, memoryGibPriceHr}]

`fetch_json` is injectable for air-gapped tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

PATH = '/vms/machine-types'

# (gpus, vcpus, mem_gib) ladder per machine type — the reference's
# spec list (fetch_cudo.py:63) in the same type grammar.
_SPECS = [(0, 8, 32), (0, 16, 64), (1, 4, 16), (1, 8, 32),
          (1, 8, 48), (1, 24, 96), (4, 32, 192), (8, 192, 768)]

# Cudo gpuModel -> canonical accelerator name.
_GPU_NAMES = {
    'RTX A4000': 'RTXA4000',
    'RTX A5000': 'RTXA5000',
    'RTX A6000': 'RTXA6000',
    'A100 PCIe 80GB': 'A100-80GB',
    'H100 PCIe': 'H100',
    'H100 SXM': 'H100-SXM',
    'V100': 'V100',
}


def _default_fetch_json(_path: str) -> Dict[str, Any]:
    from skypilot_tpu.provision.cudo import cudo_api
    return cudo_api.request('GET', PATH)


def _price(entry: Dict[str, Any], gpus: int, vcpus: int,
           mem: int) -> float:
    def _rate(key):
        value = entry.get(key)
        if isinstance(value, dict):  # {'value': '0.02'} API form
            value = value.get('value')
        return float(value or 0)
    return round(gpus * _rate('gpuPriceHr')
                 + vcpus * _rate('vcpuPriceHr')
                 + mem * _rate('memoryGibPriceHr'), 4)


def rows_from_machine_types(payload: Dict[str, Any]):
    rows = []
    seen = set()
    for entry in payload.get('machineTypes') or []:
        machine_type = str(entry.get('machineType', ''))
        if not machine_type:
            continue
        gpu_model = str(entry.get('gpuModel', '') or '')
        acc = _GPU_NAMES.get(gpu_model, gpu_model.replace(' ', ''))
        for gpus, vcpus, mem in _SPECS:
            if gpus > 0 and not gpu_model:
                continue
            if gpus == 0 and gpu_model:
                continue
            itype = f'{machine_type}_{gpus}x{vcpus}v{mem}gb'
            if itype in seen:
                continue
            price = _price(entry, gpus, vcpus, mem)
            if price <= 0:
                continue
            seen.add(itype)
            rows.append({
                'instance_type': itype,
                'vcpus': vcpus,
                'memory_gb': mem,
                'accelerator_name': acc if gpus else '',
                'accelerator_count': gpus,
                'price': price,
                'spot_price': price,  # no spot tier
            })
    return sorted(rows, key=lambda r: r['instance_type'])


def fetch_and_write(fetch_json: Optional[Callable[[str],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import cudo_catalog
    fetch_json = fetch_json or _default_fetch_json
    rows = rows_from_machine_types(fetch_json(PATH))
    if not rows:
        raise RuntimeError('Cudo machine-types API returned nothing '
                           'usable; keeping the previous table.')
    path = common.write_catalog_csv('cudo', 'vms',
                                    common.rows_to_vms_csv(rows))
    cudo_catalog.CATALOG.reload()
    return {'vms': path}
