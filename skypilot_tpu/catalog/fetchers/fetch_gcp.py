"""Regenerate the GCP price tables from the Cloud Billing Catalog API.

Reference: sky/clouds/service_catalog/data_fetchers/fetch_gcp.py:1 —
the reference pulls SKUs from the Cloud Billing Catalog API and
rebuilds its CSVs; this is the same pipeline against our table shapes:

  - `vms`: per-instance on-demand/spot prices recomputed from the
    per-core + per-GB (+ per-GPU) SKU rates of each machine family;
    the instance SHAPES (vcpus/memory/accelerators) come from the
    currently-effective table — shapes are stable, prices are not.
  - `tpu_prices`: $/chip-hour per TPU generation from the TPU SKUs.

The Billing Catalog API is public but keyed:
    GET https://cloudbilling.googleapis.com/v1/services/
        6F81-5844-456A/skus?key=<api_key>&pageSize=5000
(6F81-5844-456A = Compute Engine, which carries the TPU SKUs too.)
`fetch_json` is injectable so air-gapped tests (and this zero-egress
build environment) exercise the full parse/shape pipeline on fixture
pages.
"""
from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

BILLING_URL = ('https://cloudbilling.googleapis.com/v1/services/'
               '6F81-5844-456A/skus')
BASE_REGION = 'us-central1'

# Machine families whose instances are priced per-core + per-GB.
_FAMILIES = ('n2', 'e2', 'a2', 'g2', 'a3', 'n2d', 'c2', 'm1')
_GPU_NAMES = {
    'a100 80gb': 'A100-80GB',
    'a100': 'A100',
    'l4': 'L4',
    'h100': 'H100',
    't4': 'T4',
    'v100': 'V100',
}


def _default_fetch_json(url: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return json.loads(resp.read())


def iter_skus(api_key: Optional[str],
              fetch_json: Callable[[str], Dict[str, Any]]
              ) -> Iterator[Dict[str, Any]]:
    """Paginate the Billing Catalog SKU list."""
    page_token = ''
    while True:
        params = {'pageSize': '5000'}
        if api_key:
            params['key'] = api_key
        if page_token:
            params['pageToken'] = page_token
        url = BILLING_URL + '?' + urllib.parse.urlencode(params)
        page = fetch_json(url)
        yield from page.get('skus', [])
        page_token = page.get('nextPageToken', '')
        if not page_token:
            return


def _unit_price(sku: Dict[str, Any]) -> Optional[float]:
    """$/unit from the last (steady-state) tiered rate."""
    try:
        rates = (sku['pricingInfo'][0]['pricingExpression']
                 ['tieredRates'])
        unit = rates[-1]['unitPrice']
        return float(unit.get('units', 0) or 0) + \
            float(unit.get('nanos', 0) or 0) / 1e9
    except (KeyError, IndexError, TypeError, ValueError):
        return None


def _in_region(sku: Dict[str, Any], region: str) -> bool:
    regions = sku.get('serviceRegions', [])
    return region in regions or 'global' in regions


_TPU_GEN_RE = re.compile(r'\btpu[ -]?v(\d+[ep]?)\b', re.IGNORECASE)


class _Rates:
    """Accumulated $/h rates keyed by (kind, name, usage)."""

    def __init__(self) -> None:
        self.core: Dict[Tuple[str, str], float] = {}   # (family, usage)
        self.ram: Dict[Tuple[str, str], float] = {}
        self.gpu: Dict[Tuple[str, str], float] = {}    # (gpu_name, usage)
        self.tpu: Dict[Tuple[str, str], float] = {}    # (gen, usage)


def collect_rates(skus: Iterator[Dict[str, Any]],
                  region: str = BASE_REGION) -> _Rates:
    rates = _Rates()
    for sku in skus:
        if not _in_region(sku, region):
            continue
        cat = sku.get('category', {})
        usage = cat.get('usageType', '')
        if usage not in ('OnDemand', 'Preemptible'):
            continue
        desc = sku.get('description', '')
        low = desc.lower()
        price = _unit_price(sku)
        if price is None:
            continue
        tpu_match = _TPU_GEN_RE.search(low)
        if tpu_match or 'cloud tpu' in low:
            gen = tpu_match.group(1) if tpu_match else None
            if gen and 'pod' not in low:
                rates.tpu.setdefault((f'v{gen}', usage), price)
            continue
        group = cat.get('resourceGroup', '')
        if group == 'GPU':
            for key, name in _GPU_NAMES.items():
                if key in low:
                    rates.gpu.setdefault((name, usage), price)
                    break
            continue
        if group in ('CPU', 'RAM') or 'instance core' in low \
                or 'instance ram' in low:
            family = low.split(' ', 1)[0]
            if family not in _FAMILIES:
                continue
            if 'core' in low:
                rates.core.setdefault((family, usage), price)
            elif 'ram' in low:
                rates.ram.setdefault((family, usage), price)
    return rates


def build_vms_csv(rates: _Rates, shapes) -> Tuple[str, List[str]]:
    """Recompute the vms table prices from SKU rates.

    `shapes` is the currently-effective vms dataframe; rows whose
    family/GPU rates were not found keep their previous prices (and
    are reported in `skipped`)."""
    lines = ['instance_type,vcpus,memory_gb,accelerator_name,'
             'accelerator_count,price,spot_price']
    skipped: List[str] = []
    for _, row in shapes.iterrows():
        itype = str(row['instance_type'])
        family = itype.split('-', 1)[0]
        vcpus = float(row['vcpus'])
        mem = float(row['memory_gb'])
        acc = '' if not isinstance(row['accelerator_name'], str) \
            else row['accelerator_name']
        acc_n = int(row['accelerator_count'] or 0)

        def _price(usage: str, fallback: float) -> float:
            core = rates.core.get((family, usage))
            ram = rates.ram.get((family, usage))
            if core is None or ram is None:
                return fallback
            total = vcpus * core + mem * ram
            if acc and acc_n:
                gpu = rates.gpu.get((acc, usage))
                if gpu is None:
                    return fallback
                total += acc_n * gpu
            return round(total, 4)

        od = _price('OnDemand', float(row['price']))
        sp = _price('Preemptible', float(row['spot_price']))
        if od == float(row['price']) and sp == float(row['spot_price']):
            skipped.append(itype)
        lines.append(f'{itype},{row["vcpus"]},{row["memory_gb"]},'
                     f'{acc},{acc_n},{od},{sp}')
    return '\n'.join(lines) + '\n', skipped


def build_tpu_prices_csv(rates: _Rates,
                         current: Dict[str, Tuple[float, float]]
                         ) -> Tuple[str, List[str]]:
    lines = ['generation,price,spot_price']
    skipped: List[str] = []
    for gen in sorted(current):
        od = rates.tpu.get((gen, 'OnDemand'))
        sp = rates.tpu.get((gen, 'Preemptible'))
        cur_od, cur_sp = current[gen]
        if od is None:
            od, sp = cur_od, cur_sp
            skipped.append(gen)
        elif sp is None:
            # Spot SKU missing: keep the current spot/od ratio.
            sp = round(od * (cur_sp / cur_od), 4)
        lines.append(f'{gen},{od},{sp}')
    return '\n'.join(lines) + '\n', skipped


def fetch_and_write(api_key: Optional[str] = None,
                    fetch_json: Optional[Callable[[str],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    """Pull SKUs, rebuild `vms` + `tpu_prices`, write the overrides."""
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import gcp_catalog
    fetch_json = fetch_json or _default_fetch_json
    rates = collect_rates(iter_skus(api_key, fetch_json))
    vms_csv, vm_skipped = build_vms_csv(rates, gcp_catalog._vm_df())  # pylint: disable=protected-access
    tpu_csv, tpu_skipped = build_tpu_prices_csv(
        rates, gcp_catalog._tpu_prices())  # pylint: disable=protected-access
    if vm_skipped:
        logger.warning(
            f'No fresh rates for {len(vm_skipped)} instance types '
            f'(kept previous prices): {vm_skipped[:5]}...')
    if tpu_skipped:
        logger.warning(
            f'No fresh TPU rates for generations {tpu_skipped} '
            '(kept previous prices).')
    paths = {
        'vms': common.write_catalog_csv('gcp', 'vms', vms_csv),
        'tpu_prices': common.write_catalog_csv('gcp', 'tpu_prices',
                                               tpu_csv),
    }
    gcp_catalog.reload()
    return paths
