"""Regenerate the Lambda Cloud `vms` table from the public
instance-types API.

Reference: sky/clouds/service_catalog/data_fetchers/
fetch_lambda_cloud.py — rebuilt against the same endpoint:

    GET https://cloud.lambdalabs.com/api/v1/instance-types
    (Bearer <api key>; returns every type with price_cents_per_hour,
    specs, and the regions with capacity)

`fetch_json` is injectable for air-gapped tests.
"""
from __future__ import annotations

import json
import re
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

URL = 'https://cloud.lambdalabs.com/api/v1/instance-types'

# gpu_1x_a100_sxm4 -> A100; keep in sync with the accelerator names
# the snapshot already uses (optimizer requests match on these).
_GPU_PATTERNS = [
    (re.compile(r'a100.*80gb|8x_a100_80gb', re.I), 'A100-80GB'),
    (re.compile(r'a100', re.I), 'A100'),
    (re.compile(r'h100', re.I), 'H100'),
    (re.compile(r'gh200', re.I), 'GH200'),
    (re.compile(r'a10\b', re.I), 'A10'),
    (re.compile(r'a6000', re.I), 'A6000'),
    (re.compile(r'rtx6000', re.I), 'RTX6000'),
    (re.compile(r'v100', re.I), 'V100'),
]


def _default_fetch_json(url: str) -> Dict[str, Any]:
    from skypilot_tpu.provision.lambda_cloud import lambda_api
    key = lambda_api.load_api_key()
    if key is None:
        raise RuntimeError('Lambda catalog fetch needs an API key '
                           '(env LAMBDA_API_KEY).')
    req = urllib.request.Request(
        url, headers={'Authorization': f'Bearer {key}'})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def _gpu_name(type_name: str, description: str) -> Optional[str]:
    if not type_name.startswith('gpu_'):
        return None
    for pattern, name in _GPU_PATTERNS:
        if pattern.search(type_name) or pattern.search(description):
            return name
    return None


def _gpu_count(type_name: str) -> int:
    m = re.match(r'gpu_(\d+)x_', type_name)
    return int(m.group(1)) if m else 1


def rows_from_response(payload: Dict[str, Any]):
    """instance-types response -> vms-table rows (list of dicts)."""
    rows = []
    for entry in (payload.get('data') or {}).values():
        it = entry.get('instance_type') or {}
        name = str(it.get('name', ''))
        if not name:
            continue
        specs = it.get('specs') or {}
        price = float(it.get('price_cents_per_hour', 0)) / 100.0
        gpu = _gpu_name(name, str(it.get('description', '')))
        rows.append({
            'instance_type': name,
            'vcpus': float(specs.get('vcpus', 0) or 0),
            'memory_gb': float(specs.get('memory_gib', 0) or 0),
            'accelerator_name': gpu or '',
            'accelerator_count': _gpu_count(name) if gpu else 0,
            'price': price,
            'spot_price': price,  # no spot tier
        })
    return sorted(rows, key=lambda r: r['instance_type'])


def fetch_and_write(fetch_json: Optional[Callable[[str],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import lambda_catalog
    fetch_json = fetch_json or _default_fetch_json
    rows = rows_from_response(fetch_json(URL))
    if not rows:
        raise RuntimeError('Lambda instance-types API returned no '
                           'types; keeping the previous table.')
    path = common.write_catalog_csv('lambda', 'vms',
                                    common.rows_to_vms_csv(rows))
    lambda_catalog.reload()
    return {'vms': path}
