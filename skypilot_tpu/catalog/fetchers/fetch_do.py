"""Regenerate the DigitalOcean `vms` table from the /v2/sizes API.

DO publishes every droplet size (vcpus, memory, hourly price, region
availability) through the authenticated sizes endpoint:

    GET https://api.digitalocean.com/v2/sizes?per_page=200

`fetch_page` is injectable for air-gapped tests.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# gpu-h100x8-640gb -> (H100, 8); non-gpu sizes carry no accelerator.
_GPU_SLUG = re.compile(r'^gpu-([a-z0-9]+?)x(\d+)(?:-|$)')
_GPU_NAMES = {'h100': 'H100', 'l40s': 'L40S', 'mi300x': 'MI300X'}
# Size families worth carrying (the full list is hundreds of legacy
# slugs; the catalog keeps the modern tiers the optimizer picks from).
_FAMILIES = ('s-', 'c-', 'g-', 'm-', 'gpu-')


def _default_fetch_page(page: int) -> Dict[str, Any]:
    from skypilot_tpu.provision.do import do_api
    return do_api.request('GET', '/sizes',
                          params={'per_page': '200',
                                  'page': str(page)})


def rows_from_sizes(sizes: List[Dict[str, Any]]):
    rows = []
    for size in sizes or []:
        slug = str(size.get('slug', ''))
        if not slug.startswith(_FAMILIES) or \
                not size.get('available', True):
            continue
        acc_name, acc_count = '', 0
        m = _GPU_SLUG.match(slug)
        if m:
            acc_name = _GPU_NAMES.get(m.group(1), m.group(1).upper())
            acc_count = int(m.group(2))
        price = float(size.get('price_hourly', 0) or 0)
        if price <= 0:
            continue
        rows.append({
            'instance_type': slug,
            'vcpus': float(size.get('vcpus', 0) or 0),
            'memory_gb': float(size.get('memory', 0) or 0) / 1024.0,
            'accelerator_name': acc_name,
            'accelerator_count': acc_count,
            'price': price,
            'spot_price': price,  # no spot tier
        })
    return sorted(rows, key=lambda r: r['instance_type'])


def fetch_and_write(fetch_page: Optional[Callable[[int],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import do_catalog
    fetch_page = fetch_page or _default_fetch_page
    sizes: List[Dict[str, Any]] = []
    page = 1
    while True:
        resp = fetch_page(page)
        batch = list(resp.get('sizes') or [])
        sizes.extend(batch)
        if not resp.get('links', {}).get('pages', {}).get('next'):
            break
        page += 1
    rows = rows_from_sizes(sizes)
    if not rows:
        raise RuntimeError('DigitalOcean sizes API returned no usable '
                           'sizes; keeping the previous table.')
    path = common.write_catalog_csv('do', 'vms',
                                    common.rows_to_vms_csv(rows))
    do_catalog.reload()
    return {'vms': path}
