"""Regenerate the AWS `vms` table from the public EC2 pricing offers.

Reference: sky/clouds/service_catalog/data_fetchers/fetch_aws.py —
rebuilt against the unauthenticated regional offers JSON:

    GET https://pricing.us-east-1.amazonaws.com/offers/v1.0/aws/
        AmazonEC2/current/<region>/index.json

(no SigV4 needed).  On-demand prices come straight from the offer's
price dimensions; spot prices are NOT in the offers file (the spot API
requires credentials), so each instance keeps its current spot/OD
ratio applied to the fresh OD price — explicitly logged.

`fetch_json` is injectable for air-gapped tests; the real file is
hundreds of MB, so the parser streams nothing and filters to the
catalog's instance shapes only.
"""
from __future__ import annotations

import json
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

OFFERS_URL = ('https://pricing.us-east-1.amazonaws.com/offers/v1.0/'
              'aws/AmazonEC2/current/{region}/index.json')
BASE_REGION = 'us-east-1'


def _default_fetch_json(url: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=300) as resp:
        return json.loads(resp.read())


def extract_od_prices(offer: Dict[str, Any],
                      wanted: set) -> Dict[str, float]:
    """instanceType -> on-demand $/h for plain Linux/Shared capacity."""
    skus = {}
    for sku, product in offer.get('products', {}).items():
        attrs = product.get('attributes', {})
        if product.get('productFamily') != 'Compute Instance':
            continue
        itype = attrs.get('instanceType')
        if itype not in wanted:
            continue
        if (attrs.get('tenancy') != 'Shared'
                or attrs.get('operatingSystem') != 'Linux'
                or attrs.get('preInstalledSw') not in (None, 'NA')
                or attrs.get('capacitystatus') not in (None, 'Used')):
            continue
        skus[sku] = itype
    prices: Dict[str, float] = {}
    on_demand = offer.get('terms', {}).get('OnDemand', {})
    for sku, itype in skus.items():
        for term in on_demand.get(sku, {}).values():
            for dim in term.get('priceDimensions', {}).values():
                usd = dim.get('pricePerUnit', {}).get('USD')
                if usd is not None and float(usd) > 0:
                    prices[itype] = float(usd)
    return prices


def fetch_and_write(region: str = BASE_REGION,
                    fetch_json: Optional[Callable[[str],
                                                  Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import aws_catalog
    from skypilot_tpu.catalog import common
    fetch_json = fetch_json or _default_fetch_json
    shapes = aws_catalog._vm_df()  # pylint: disable=protected-access
    wanted = set(shapes['instance_type'])
    offer = fetch_json(OFFERS_URL.format(region=region))
    prices = extract_od_prices(offer, wanted)
    # Table stores BASE_REGION anchors; normalize other regions back
    # through the catalog's own multiplier (see fetch_azure).
    divisor = aws_catalog._REGION_PRICE_MULTIPLIER.get(region, 1.2)  # pylint: disable=protected-access
    if divisor != 1.0:
        logger.info(f'Normalizing {region} prices to '
                    f'{BASE_REGION} anchors (/{divisor}).')
        prices = {k: v / divisor for k, v in prices.items()}

    lines = ['instance_type,vcpus,memory_gb,accelerator_name,'
             'accelerator_count,price,spot_price']
    skipped = []
    for _, row in shapes.iterrows():
        itype = str(row['instance_type'])
        od = prices.get(itype)
        cur_od, cur_sp = float(row['price']), float(row['spot_price'])
        if od is None:
            od, sp = cur_od, cur_sp
            skipped.append(itype)
        else:
            sp = round(od * (cur_sp / cur_od), 4)
        acc = '' if not isinstance(row['accelerator_name'], str) \
            else row['accelerator_name']
        lines.append(f'{itype},{row["vcpus"]},{row["memory_gb"]},'
                     f'{acc},{int(row["accelerator_count"] or 0)},'
                     f'{od},{sp}')
    if skipped:
        logger.warning(
            f'No fresh OD price for {skipped} (kept previous).')
    logger.info('Spot prices derived from fresh OD x previous spot/OD '
                'ratio (offers file carries no spot rates).')
    path = common.write_catalog_csv('aws', 'vms',
                                    '\n'.join(lines) + '\n')
    aws_catalog.reload()
    return {'vms': path}
