"""Catalog data fetchers: regenerate the price tables from the clouds'
public pricing endpoints (reference:
sky/clouds/service_catalog/data_fetchers/fetch_gcp.py etc.).

`sky catalog update --fetch gcp|aws` writes fresh CSVs into the
override cache (`catalog/common.py` tiering), so the shipped in-code
snapshot is a fallback, not a slowly-rotting source of truth.
"""
from __future__ import annotations

import importlib
from typing import Dict

# cloud -> fetcher module name.  FETCHABLE derives from this table,
# so the staleness warning's --fetch hint (catalog/common.py) is
# structurally tied to the dispatch: adding a fetcher here updates
# both.
_FETCHERS = {
    'gcp': 'fetch_gcp',
    'aws': 'fetch_aws',
    'azure': 'fetch_azure',
    'lambda': 'fetch_lambda',
    'runpod': 'fetch_runpod',
    'do': 'fetch_do',
    'fluidstack': 'fetch_fluidstack',
    'cudo': 'fetch_cudo',
    'vsphere': 'fetch_vsphere',
}
FETCHABLE = frozenset(_FETCHERS)


def fetch(cloud: str, **kwargs) -> Dict[str, str]:
    """Regenerate `cloud`'s tables; returns {table: written_path}."""
    module_name = _FETCHERS.get(cloud)
    if module_name is None:
        raise ValueError(f'No catalog fetcher for cloud {cloud!r}.')
    module = importlib.import_module(
        f'skypilot_tpu.catalog.fetchers.{module_name}')
    return module.fetch_and_write(**kwargs)
