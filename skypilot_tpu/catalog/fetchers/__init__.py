"""Catalog data fetchers: regenerate the price tables from the clouds'
public pricing endpoints (reference:
sky/clouds/service_catalog/data_fetchers/fetch_gcp.py etc.).

`sky catalog update --fetch gcp|aws` writes fresh CSVs into the
override cache (`catalog/common.py` tiering), so the shipped in-code
snapshot is a fallback, not a slowly-rotting source of truth.
"""
from __future__ import annotations

from typing import Dict

# The clouds `fetch` can regenerate — the staleness warning in
# catalog/common.py keys its --fetch hint off this, so it cannot
# drift from the dispatch below.
FETCHABLE = frozenset(
    ('gcp', 'aws', 'azure', 'lambda', 'runpod', 'do', 'fluidstack'))


def fetch(cloud: str, **kwargs) -> Dict[str, str]:
    """Regenerate `cloud`'s tables; returns {table: written_path}."""
    if cloud == 'gcp':
        from skypilot_tpu.catalog.fetchers import fetch_gcp
        return fetch_gcp.fetch_and_write(**kwargs)
    if cloud == 'aws':
        from skypilot_tpu.catalog.fetchers import fetch_aws
        return fetch_aws.fetch_and_write(**kwargs)
    if cloud == 'azure':
        from skypilot_tpu.catalog.fetchers import fetch_azure
        return fetch_azure.fetch_and_write(**kwargs)
    if cloud == 'lambda':
        from skypilot_tpu.catalog.fetchers import fetch_lambda
        return fetch_lambda.fetch_and_write(**kwargs)
    if cloud == 'runpod':
        from skypilot_tpu.catalog.fetchers import fetch_runpod
        return fetch_runpod.fetch_and_write(**kwargs)
    if cloud == 'do':
        from skypilot_tpu.catalog.fetchers import fetch_do
        return fetch_do.fetch_and_write(**kwargs)
    if cloud == 'fluidstack':
        from skypilot_tpu.catalog.fetchers import fetch_fluidstack
        return fetch_fluidstack.fetch_and_write(**kwargs)
    raise ValueError(f'No catalog fetcher for cloud {cloud!r}.')
