"""Regenerate the RunPod `vms` table from the GPU-types GraphQL query.

Counterpart of the reference's runpod catalog refresh — RunPod
publishes per-GPU prices through the same GraphQL API the provisioner
uses:

    query { gpuTypes { id displayName memoryInGb securePrice
                       communityPrice secureSpotPrice
                       communitySpotPrice } }

`run_query` is injectable for air-gapped tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

QUERY = ('query GpuTypes { gpuTypes { id displayName memoryInGb '
         'securePrice communityPrice secureSpotPrice '
         'communitySpotPrice } }')

# displayName -> catalog accelerator token (instance types are
# <n>x_<token>_<TIER>, matching the shipped snapshot's grammar).
_NAME_TOKENS = {
    'NVIDIA A100 80GB PCIe': 'A100-80GB',
    'NVIDIA A100-SXM4-80GB': 'A100-80GB-SXM',
    'NVIDIA A40': 'A40',
    'NVIDIA L40S': 'L40S',
    'NVIDIA GeForce RTX 4090': 'RTX4090',
    'NVIDIA H100 PCIe': 'H100',
    'NVIDIA H100 80GB HBM3': 'H100-SXM',
}
_COUNTS = (1, 2, 4, 8)


def _default_run_query(query: str) -> Dict[str, Any]:
    from skypilot_tpu.provision.runpod import runpod_api
    return runpod_api._call(query)  # pylint: disable=protected-access


def rows_from_gpu_types(gpu_types: List[Dict[str, Any]],
                        known_shapes: Optional[Dict[str, tuple]] = None):
    """gpuTypes -> vms rows.  The query prices GPUs; it does NOT
    describe host shapes (memoryInGb is VRAM).  Host vcpus/memory come
    from `known_shapes` (the current table — only PRICES refresh for
    known types; a refresh must never shrink a pod's advertised shape
    and break cpus=/memory= requests that resolved before); brand-new
    GPU types fall back to RunPod's published per-GPU allotments."""
    known_shapes = known_shapes or {}
    rows = []
    for gpu in gpu_types or []:
        token = _NAME_TOKENS.get(str(gpu.get('displayName', '')))
        if token is None:
            continue
        vram = float(gpu.get('memoryInGb', 0) or 0)
        for tier, price_key, spot_key in (
                ('SECURE', 'securePrice', 'secureSpotPrice'),
                ('COMMUNITY', 'communityPrice', 'communitySpotPrice')):
            od = float(gpu.get(price_key) or 0)
            if od <= 0:
                continue
            spot = float(gpu.get(spot_key) or 0) or od
            for count in _COUNTS:
                itype = f'{count}x_{token}_{tier}'
                vcpus, mem = known_shapes.get(itype) or (
                    (12 if vram >= 80 else 8) * count,
                    max(vram, 8) * count + 16 * count)
                rows.append({
                    'instance_type': itype,
                    'vcpus': vcpus,
                    'memory_gb': mem,
                    'accelerator_name': token,
                    'accelerator_count': count,
                    'price': round(od * count, 4),
                    'spot_price': round(spot * count, 4),
                })
    return sorted(rows, key=lambda r: r['instance_type'])


def fetch_and_write(run_query: Optional[Callable[[str],
                                                 Dict[str, Any]]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import runpod_catalog
    run_query = run_query or _default_run_query
    data = run_query(QUERY)
    current = runpod_catalog._vm_df()  # pylint: disable=protected-access
    known_shapes = {
        str(r['instance_type']): (float(r['vcpus']),
                                  float(r['memory_gb']))
        for _, r in current.iterrows()}
    rows = rows_from_gpu_types(list(data.get('gpuTypes') or []),
                               known_shapes)
    if not rows:
        raise RuntimeError('RunPod gpuTypes query returned nothing '
                           'usable; keeping the previous table.')
    path = common.write_catalog_csv('runpod', 'vms',
                                    common.rows_to_vms_csv(rows))
    runpod_catalog.reload()
    return {'vms': path}
