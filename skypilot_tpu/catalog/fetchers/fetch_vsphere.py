"""Regenerate the vSphere `vms` table from vCenter inventory.

Reference: sky/clouds/service_catalog/data_fetchers/fetch_vsphere.py —
it walks the vCenter host inventory (pyvmomi) and emits shapes the
site can actually schedule.  Here the same walk rides the vCenter
Automation REST API the provisioner already uses
(GET /api/vcenter/host -> connected hosts).

Preset shapes are emitted only up to the LARGEST connected host, and
GPU presets only when config `vsphere.gpu_presets` opts in (the REST
host summary carries no GPU inventory, so "has GPUs" is the site
operator's call) — an on-prem catalog must not advertise shapes the
site cannot place.  Prices are chargeback anchors carried over from
the current table, falling back to the built-in snapshot's anchors.
`fetch_json` is injectable for air-gapped tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# (instance_type, vcpus, mem_gb, acc_name, acc_count) presets; the
# fetch trims this to what the site's hosts can hold.
_PRESETS = [
    ('cpu-small', 4, 16, '', 0),
    ('cpu-medium', 8, 32, '', 0),
    ('cpu-large', 16, 64, '', 0),
    ('cpu-xlarge', 32, 128, '', 0),
    ('gpu-t4-8x32', 8, 32, 'T4', 1),
    ('gpu-v100-8x64', 8, 64, 'V100', 1),
    ('gpu-a100-16x128', 16, 128, 'A100', 1),
]


def _default_fetch_json(path: str) -> Any:
    from skypilot_tpu.provision.vsphere import vsphere_api
    return vsphere_api.request('GET', path)


def rows_from_hosts(hosts: List[Dict[str, Any]],
                    current_prices: Dict[str, float],
                    gpu_presets: bool):
    """Trim the preset ladder to the largest CONNECTED host."""
    connected = [h for h in hosts or []
                 if str(h.get('connection_state', '')).upper()
                 == 'CONNECTED']
    if not connected:
        return []
    max_cpu = max(int(h.get('cpu_count', 0) or 0)
                  for h in connected)
    max_mem = max(float(h.get('memory_size_MiB', 0) or 0) / 1024.0
                  for h in connected)
    # Hosts that don't report capacity still serve the full ladder
    # (the REST summary omits these fields on some vCenter versions).
    if max_cpu <= 0:
        max_cpu, max_mem = 1 << 30, float(1 << 30)
    rows = []
    for itype, vcpus, mem, acc, count in _PRESETS:
        if count > 0 and not gpu_presets:
            logger.info(f'vsphere fetch: dropping {itype} — set '
                        'config vsphere.gpu_presets: true if this '
                        'site has passthrough GPUs.')
            continue
        if vcpus > max_cpu or mem > max_mem:
            logger.info(f'vsphere fetch: dropping {itype} '
                        f'({vcpus}v/{mem}g exceeds the largest host '
                        f'{max_cpu}v/{max_mem:.0f}g).')
            continue
        price = current_prices.get(itype, 0.05 * (vcpus / 4))
        rows.append({
            'instance_type': itype,
            'vcpus': vcpus,
            'memory_gb': mem,
            'accelerator_name': acc,
            'accelerator_count': count,
            'price': price,
            'spot_price': price,
        })
    return rows


def fetch_and_write(fetch_json: Optional[Callable[[str], Any]] = None
                    ) -> Dict[str, str]:
    from skypilot_tpu.catalog import common
    from skypilot_tpu.catalog import vsphere_catalog
    import io

    import pandas as pd

    from skypilot_tpu import config as config_lib
    fetch_json = fetch_json or _default_fetch_json
    hosts = fetch_json('/api/vcenter/host') or []
    # Chargeback anchors: snapshot prices UNDER the current (possibly
    # trimmed) override — a preset dropped by an earlier fetch must
    # come back at its real anchor, not a formula guess.
    current = {
        str(r['instance_type']): float(r['price'])
        for _, r in pd.read_csv(io.StringIO(
            vsphere_catalog._VMS_CSV)).iterrows()}  # pylint: disable=protected-access
    current.update({
        str(r['instance_type']): float(r['price'])
        for _, r in vsphere_catalog.CATALOG._vm_df().iterrows()})  # pylint: disable=protected-access
    gpu_presets = bool(config_lib.get_nested(
        ('vsphere', 'gpu_presets'), False))
    rows = rows_from_hosts(hosts, current, gpu_presets)
    if not rows:
        raise RuntimeError('no CONNECTED vCenter hosts; keeping the '
                           'previous table.')
    path = common.write_catalog_csv('vsphere', 'vms',
                                    common.rows_to_vms_csv(rows))
    vsphere_catalog.CATALOG.reload()
    return {'vms': path}
