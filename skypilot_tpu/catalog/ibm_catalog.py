"""IBM Cloud VPC catalog (reference service_catalog ibm tier).

VPC Gen2 profiles: bx2/cx2 CPU tiers + gx2/gx3 GPU profiles
(V100 / L4 / L40S); flat hourly pricing, no spot.
"""
from skypilot_tpu.catalog import flat

_VMS_CSV = """\
instance_type,vcpus,memory_gb,accelerator_name,accelerator_count,price,spot_price
bx2-8x32,8,32,,0,0.384,0.384
bx2-16x64,16,64,,0,0.768,0.768
cx2-8x16,8,16,,0,0.336,0.336
cx2-16x32,16,32,,0,0.672,0.672
gx2-8x64x1v100,8,64,V100,1,2.48,2.48
gx2-16x128x2v100,16,128,V100,2,4.96,4.96
gx3-16x80x1l4,16,80,L4,1,1.40,1.40
gx3-32x160x2l4,32,160,L4,2,2.80,2.80
gx3-24x120x1l40s,24,120,L40S,1,2.13,2.13
gx3-48x240x2l40s,48,240,L40S,2,4.26,4.26
"""

CATALOG = flat.FlatCatalog(
    'ibm', _VMS_CSV,
    regions=['us-south', 'us-east', 'eu-gb', 'eu-de', 'jp-tok',
             'au-syd', 'ca-tor', 'br-sao'],
    snapshot_date='2025-03-01', display_name='IBM')
