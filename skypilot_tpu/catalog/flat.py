"""FlatCatalog: the shared engine behind every minor-cloud catalog.

The minor-cloud tail (Lambda/RunPod/DO/FluidStack/Cudo/Paperspace/
IBM/OCI/SCP/vSphere — reference sky/clouds/service_catalog/*_catalog.py)
all price from one flat vms table: instance_type, shape, accelerator,
price, spot_price.  One class holds the selection/pricing logic; each
per-cloud catalog is just a CSV snapshot + a region list + flags.
"""
from __future__ import annotations

import io
import typing
from typing import Dict, List, Optional, Sequence, Tuple

if typing.TYPE_CHECKING:
    import pandas as pd

from skypilot_tpu import exceptions

_VM_COLUMNS = ['instance_type', 'vcpus', 'memory_gb',
               'accelerator_name', 'accelerator_count', 'price',
               'spot_price']


class FlatCatalog:
    """Flat per-type pricing over a vms CSV with the standard columns.

    cache-dir overrides (`~/.skytpu/catalogs/v1/<cloud>/vms.csv`) and
    snapshot-staleness warnings ride catalog/common.py exactly like
    the hand-written major catalogs.
    """

    def __init__(self, cloud: str, vms_csv: str,
                 regions: Sequence[str], snapshot_date: str,
                 *, has_spot: bool = False,
                 gpu_only: bool = False,
                 display_name: Optional[str] = None) -> None:
        self.cloud = cloud
        self.display_name = display_name or cloud
        self._vms_csv = vms_csv
        self._regions = list(regions)
        self.SNAPSHOT_DATE = snapshot_date
        self.has_spot = has_spot
        self.gpu_only = gpu_only
        self._df: Optional['pd.DataFrame'] = None

    # -- table ------------------------------------------------------------
    def _vm_df(self) -> 'pd.DataFrame':
        if self._df is None:
            import pandas as pd

            from skypilot_tpu.catalog import common
            self._df = common.read_catalog_csv(self.cloud, 'vms',
                                               _VM_COLUMNS)
            if self._df is None:
                common.warn_if_snapshot_stale(self.cloud,
                                              self.SNAPSHOT_DATE)
                self._df = pd.read_csv(io.StringIO(self._vms_csv))
        return self._df

    def reload(self) -> None:
        self._df = None

    def export_snapshot(self) -> Dict[str, str]:
        return {'vms': self._vm_df().to_csv(index=False)}

    # -- lookups ----------------------------------------------------------
    def regions(self) -> List[str]:
        return list(self._regions)

    def instance_type_exists(self, instance_type: str) -> bool:
        df = self._vm_df()
        return bool((df['instance_type'] == instance_type).any())

    def _row(self, instance_type: str):
        df = self._vm_df()
        rows = df[df['instance_type'] == instance_type]
        if rows.empty:
            raise exceptions.ResourcesUnavailableError(
                f'No {self.display_name} instance type '
                f'{instance_type!r}; have '
                f'{sorted(df["instance_type"])}')
        return rows.iloc[0]

    def get_hourly_cost(self, instance_type: str, use_spot: bool,
                        region: Optional[str] = None,
                        zone: Optional[str] = None) -> float:
        del region, zone  # flat pricing across regions
        row = self._row(instance_type)
        if use_spot and self.has_spot:
            return float(row['spot_price'])
        return float(row['price'])

    def get_vcpus_mem_from_instance_type(
            self, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        row = self._row(instance_type)
        return float(row['vcpus']), float(row['memory_gb'])

    def get_accelerators_from_instance_type(
            self, instance_type: str) -> Optional[Dict[str, int]]:
        row = self._row(instance_type)
        if not row['accelerator_name'] or \
                str(row['accelerator_name']) == 'nan':
            return None
        return {str(row['accelerator_name']):
                int(row['accelerator_count'])}

    def get_default_instance_type(self, cpus: Optional[str] = None,
                                  memory: Optional[str] = None,
                                  disk_tier: Optional[str] = None
                                  ) -> Optional[str]:
        del disk_tier
        from skypilot_tpu.catalog import common
        return common.pick_default_instance_type(
            self._vm_df(), cpus, memory,
            allow_accelerators=self.gpu_only)

    def get_instance_type_for_accelerator(
            self, acc_name: str, acc_count: int) -> List[str]:
        df = self._vm_df()
        rows = df[(df['accelerator_name'] == acc_name)
                  & (df['accelerator_count'] == acc_count)]
        return list(rows.sort_values(['price', 'instance_type'])
                    ['instance_type'])

    def get_accelerator_hourly_cost(self, acc_name: str,
                                    acc_count: int, use_spot: bool,
                                    region: Optional[str] = None,
                                    zone: Optional[str] = None
                                    ) -> float:
        types = self.get_instance_type_for_accelerator(acc_name,
                                                       acc_count)
        if not types:
            raise exceptions.ResourcesUnavailableError(
                f'No {self.display_name} instance type offers '
                f'{acc_name}:{acc_count}.')
        return min(self.get_hourly_cost(t, use_spot, region, zone)
                   for t in types)

    def list_accelerators(self, name_filter: Optional[str] = None
                          ) -> Dict[str, List[Dict[str, object]]]:
        df = self._vm_df()
        out: Dict[str, List[Dict[str, object]]] = {}
        for _, row in df[df['accelerator_count'] > 0].iterrows():
            name = str(row['accelerator_name'])
            if name_filter and \
                    name_filter.lower() not in name.lower():
                continue
            out.setdefault(name, []).append({
                'accelerator_count': int(row['accelerator_count']),
                'instance_type': str(row['instance_type']),
                'price': float(row['price']),
                'spot_price': float(row['spot_price']),
            })
        return out
