"""Managed-jobs state: SQLite tables + the ManagedJobStatus state machine.

Counterpart of the reference's sky/jobs/state.py (1,030 LoC): the `spot`
per-task rows and `job_info` per-job rows, with the
PENDING→SUBMITTED→STARTING→RUNNING→RECOVERING→terminal lifecycle
(sky/jobs/state.py:186).  The DB lives client-side (our controller runs
as a local process/thread rather than on a controller VM — a deliberate
TPU-native shift: no controller cluster to provision means
seconds-not-minutes to first recovery loop; process mode keeps the
reference's isolation).
"""
from __future__ import annotations

import enum
import json
import os
import pathlib
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import paths

logger = sky_logging.init_logger(__name__)

_lock = threading.RLock()


class ManagedJobStatus(enum.Enum):
    """Reference sky/jobs/state.py:186 ManagedJobStatus."""
    PENDING = 'PENDING'
    SUBMITTED = 'SUBMITTED'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    RECOVERING = 'RECOVERING'
    CANCELLING = 'CANCELLING'
    # Terminal.
    SUCCEEDED = 'SUCCEEDED'
    CANCELLED = 'CANCELLED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_PRECHECKS = 'FAILED_PRECHECKS'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    def is_failed(self) -> bool:
        return self in (self.FAILED, self.FAILED_SETUP,
                        self.FAILED_PRECHECKS, self.FAILED_NO_RESOURCE,
                        self.FAILED_CONTROLLER)

    def colored_str(self) -> str:
        return self.value


_TERMINAL = {
    ManagedJobStatus.SUCCEEDED, ManagedJobStatus.CANCELLED,
    ManagedJobStatus.FAILED, ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS, ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
}


class ScheduleState(enum.Enum):
    """Controller-wide scheduling state of a job (reference
    sky/jobs/scheduler.py state machine)."""
    WAITING = 'WAITING'
    LAUNCHING = 'LAUNCHING'
    ALIVE = 'ALIVE'
    DONE = 'DONE'


def jobs_dir() -> str:
    d = os.path.join(paths.state_dir(), 'managed_jobs')
    os.makedirs(d, exist_ok=True)
    return d


def _db_path() -> str:
    return os.path.join(jobs_dir(), 'managed_jobs.db')


_local = threading.local()


def _conn() -> sqlite3.Connection:
    """Thread-local cached connection (keyed by DB path — tests swap the
    state dir per test); schema is created once per connection."""
    path = _db_path()
    cache = getattr(_local, 'conns', None)
    if cache is None:
        cache = _local.conns = {}
    conn = cache.get(path)
    if conn is not None:
        return conn
    conn = sqlite3.connect(path, timeout=10)
    conn.execute("""CREATE TABLE IF NOT EXISTS job_info (
        job_id INTEGER PRIMARY KEY AUTOINCREMENT,
        name TEXT,
        dag_yaml_path TEXT,
        schedule_state TEXT DEFAULT 'WAITING',
        controller_pid INTEGER,
        submitted_at REAL)""")
    conn.execute("""CREATE TABLE IF NOT EXISTS spot (
        job_id INTEGER,
        task_id INTEGER DEFAULT 0,
        task_name TEXT,
        status TEXT,
        cluster_name TEXT,
        submitted_at REAL,
        start_at REAL,
        end_at REAL,
        last_recovered_at REAL DEFAULT -1,
        recovery_count INTEGER DEFAULT 0,
        failure_reason TEXT,
        resources_str TEXT,
        PRIMARY KEY (job_id, task_id))""")
    conn.commit()
    cache[path] = conn
    return conn


def reset_for_tests() -> None:
    with _lock:
        cache = getattr(_local, 'conns', None)
        if cache:
            for conn in cache.values():
                conn.close()
            cache.clear()
        try:
            os.remove(_db_path())
        except FileNotFoundError:
            pass
        for name in os.listdir(jobs_dir()):
            if name.startswith('cancel_'):
                os.remove(os.path.join(jobs_dir(), name))


# -- job creation ----------------------------------------------------------
def set_job_info(name: Optional[str], dag_yaml_path: str) -> int:
    """Create the job row; returns the new job_id."""
    with _lock, _conn() as conn:
        cur = conn.execute(
            'INSERT INTO job_info (name, dag_yaml_path, submitted_at) '
            'VALUES (?, ?, ?)', (name, dag_yaml_path, time.time()))
        return int(cur.lastrowid)


def set_pending(job_id: int, task_id: int, task_name: Optional[str],
                resources_str: str) -> None:
    with _lock, _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO spot (job_id, task_id, task_name, '
            'status, submitted_at, resources_str) VALUES (?, ?, ?, ?, ?, ?)',
            (job_id, task_id, task_name, ManagedJobStatus.PENDING.value,
             time.time(), resources_str))


# -- state transitions (reference state.py set_* family) -------------------
def _set(job_id: int, task_id: int, **fields: Any) -> None:
    cols = ', '.join(f'{k} = ?' for k in fields)
    with _lock, _conn() as conn:
        conn.execute(
            f'UPDATE spot SET {cols} WHERE job_id = ? AND task_id = ?',
            (*fields.values(), job_id, task_id))


def set_submitted(job_id: int, task_id: int, cluster_name: str) -> None:
    _set(job_id, task_id, status=ManagedJobStatus.SUBMITTED.value,
         cluster_name=cluster_name)


def set_starting(job_id: int, task_id: int) -> None:
    _set(job_id, task_id, status=ManagedJobStatus.STARTING.value)


def set_started(job_id: int, task_id: int, start_time: float) -> None:
    _set(job_id, task_id, status=ManagedJobStatus.RUNNING.value,
         start_at=start_time, last_recovered_at=start_time)


def set_recovering(job_id: int, task_id: int) -> None:
    _set(job_id, task_id, status=ManagedJobStatus.RECOVERING.value)


def set_recovered(job_id: int, task_id: int, recovered_time: float) -> None:
    with _lock, _conn() as conn:
        conn.execute(
            'UPDATE spot SET status = ?, last_recovered_at = ?, '
            'recovery_count = recovery_count + 1 '
            'WHERE job_id = ? AND task_id = ?',
            (ManagedJobStatus.RUNNING.value, recovered_time, job_id,
             task_id))


def set_succeeded(job_id: int, task_id: int, end_time: float) -> None:
    _set(job_id, task_id, status=ManagedJobStatus.SUCCEEDED.value,
         end_at=end_time)


def set_failed(job_id: int, task_id: Optional[int],
               failure_type: ManagedJobStatus,
               failure_reason: str,
               end_time: Optional[float] = None) -> None:
    assert failure_type.is_failed(), failure_type
    end_time = time.time() if end_time is None else end_time
    with _lock, _conn() as conn:
        where = 'job_id = ?'
        args: List[Any] = [failure_type.value, failure_reason, end_time,
                           job_id]
        if task_id is not None:
            where += ' AND task_id = ?'
            args.append(task_id)
        # Only non-terminal rows move to failed (a SUCCEEDED earlier
        # pipeline stage stays SUCCEEDED).
        conn.execute(
            f'UPDATE spot SET status = ?, failure_reason = ?, end_at = ? '
            f'WHERE {where} AND status NOT IN '
            f'({",".join(repr(s.value) for s in _TERMINAL)})', args)


def set_cancelling(job_id: int) -> None:
    with _lock, _conn() as conn:
        conn.execute(
            'UPDATE spot SET status = ? WHERE job_id = ? AND status NOT IN '
            f'({",".join(repr(s.value) for s in _TERMINAL)})',
            (ManagedJobStatus.CANCELLING.value, job_id))


def set_cancelled(job_id: int) -> None:
    with _lock, _conn() as conn:
        conn.execute(
            'UPDATE spot SET status = ?, end_at = ? '
            'WHERE job_id = ? AND status = ?',
            (ManagedJobStatus.CANCELLED.value, time.time(), job_id,
             ManagedJobStatus.CANCELLING.value))


# -- queries ---------------------------------------------------------------
def get_status(job_id: int) -> Optional[ManagedJobStatus]:
    """Aggregate job status = the first non-terminal task's status, else
    the last task's terminal status (reference get_status semantics for
    pipelines)."""
    rows = get_job_tasks(job_id)
    if not rows:
        return None
    for row in rows:
        st = ManagedJobStatus(row['status'])
        if not st.is_terminal():
            return st
        if st != ManagedJobStatus.SUCCEEDED:
            return st
    return ManagedJobStatus(rows[-1]['status'])


def get_job_tasks(job_id: int) -> List[Dict[str, Any]]:
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT job_id, task_id, task_name, status, cluster_name, '
            'submitted_at, start_at, end_at, last_recovered_at, '
            'recovery_count, failure_reason, resources_str FROM spot '
            'WHERE job_id = ? ORDER BY task_id', (job_id,))
        return [_row_to_dict(r) for r in cur.fetchall()]


def get_managed_jobs() -> List[Dict[str, Any]]:
    """All jobs, newest first, one record per (job, task)."""
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT s.job_id, s.task_id, s.task_name, s.status, '
            's.cluster_name, s.submitted_at, s.start_at, s.end_at, '
            's.last_recovered_at, s.recovery_count, s.failure_reason, '
            's.resources_str, j.name, j.schedule_state, j.controller_pid '
            'FROM spot s JOIN job_info j ON s.job_id = j.job_id '
            'ORDER BY s.job_id DESC, s.task_id')
        out = []
        for r in cur.fetchall():
            d = _row_to_dict(r[:12])
            d['job_name'] = r[12] if r[12] is not None else d['task_name']
            d['schedule_state'] = r[13]
            d['controller_pid'] = r[14]
            out.append(d)
        return out


def get_job_ids_by_name(name: str) -> List[int]:
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT job_id FROM job_info WHERE name = ? '
            'ORDER BY job_id DESC', (name,))
        return [int(r[0]) for r in cur.fetchall()]


def get_job_info(job_id: int) -> Optional[Dict[str, Any]]:
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT job_id, name, dag_yaml_path, schedule_state, '
            'controller_pid, submitted_at FROM job_info WHERE job_id = ?',
            (job_id,))
        row = cur.fetchone()
        if row is None:
            return None
        return {
            'job_id': row[0], 'name': row[1], 'dag_yaml_path': row[2],
            'schedule_state': ScheduleState(row[3]),
            'controller_pid': row[4], 'submitted_at': row[5],
        }


def _row_to_dict(row: tuple) -> Dict[str, Any]:
    status = ManagedJobStatus(row[3])
    end = row[7]
    start = row[6]
    duration = (end - start) if (start and end) else (
        (time.time() - start) if start and not status.is_terminal() else
        None)
    return {
        'job_id': row[0], 'task_id': row[1], 'task_name': row[2],
        'status': status, 'cluster_name': row[4], 'submitted_at': row[5],
        'start_at': start, 'end_at': end, 'last_recovered_at': row[8],
        'recovery_count': row[9], 'failure_reason': row[10],
        'resources_str': row[11], 'job_duration': duration,
    }


# -- scheduler state (reference sky/jobs/scheduler.py over job_info) -------
def scheduler_lock() -> filelock.FileLock:
    return filelock.FileLock(
        os.path.join(paths.locks_dir(), 'managed_jobs_scheduler.lock'),
        timeout=30)


def set_schedule_state(job_id: int, state: ScheduleState) -> None:
    with _lock, _conn() as conn:
        conn.execute(
            'UPDATE job_info SET schedule_state = ? WHERE job_id = ?',
            (state.value, job_id))


def set_controller_pid(job_id: int, controller_pid: int) -> None:
    """Record the controller's pid without touching schedule_state (the
    spawned controller may already have advanced it)."""
    with _lock, _conn() as conn:
        conn.execute(
            'UPDATE job_info SET controller_pid = ? WHERE job_id = ?',
            (controller_pid, job_id))


def count_schedule_states(states: List[ScheduleState]) -> int:
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT COUNT(*) FROM job_info WHERE schedule_state IN '
            f'({",".join("?" * len(states))})', [s.value for s in states])
        return int(cur.fetchone()[0])


def get_waiting_job_ids() -> List[int]:
    with _lock, _conn() as conn:
        cur = conn.execute(
            'SELECT job_id FROM job_info WHERE schedule_state = ? '
            'ORDER BY job_id', (ScheduleState.WAITING.value,))
        return [int(r[0]) for r in cur.fetchall()]


# -- cancel signalling (reference jobs/utils.py cancellation file) ---------
def _cancel_flag_path(job_id: int) -> str:
    return os.path.join(jobs_dir(), f'cancel_{job_id}')


def signal_cancel(job_id: int) -> None:
    pathlib.Path(_cancel_flag_path(job_id)).touch()


def cancel_requested(job_id: int) -> bool:
    return os.path.exists(_cancel_flag_path(job_id))


def clear_cancel(job_id: int) -> None:
    try:
        os.remove(_cancel_flag_path(job_id))
    except FileNotFoundError:
        pass


# -- controller event log (observability; reference logs per-job under
#    ~/.sky/jobs/) ---------------------------------------------------------
def controller_log_path(job_id: int) -> str:
    d = os.path.join(jobs_dir(), 'controller_logs')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'job_{job_id}.log')


def append_event(job_id: int, event: str, **kv: Any) -> None:
    rec = {'ts': time.time(), 'event': event, **kv}
    with open(controller_log_path(job_id), 'a', encoding='utf-8') as f:
        f.write(json.dumps(rec) + '\n')
