"""Preemption-recovery strategies for managed jobs.

Counterpart of the reference's sky/jobs/recovery_strategy.py: a
`StrategyExecutor` base with a name registry (`__init_subclass__`,
recovery_strategy.py:71), `FAILOVER` (:388 — retry the last-used
region/zone first, then fail over) and `EAGER_NEXT_REGION` (:471, the
default — immediately blocklist the preempted region and move on, because
a preempted zone usually stays capacity-starved for a while).

TPU-specific semantics baked in:
  - a preempted TPU-VM slice must be *deleted*, never stopped
    (`Resources.need_cleanup_after_preemption_or_failure`, reference
    resources.py:633) — `cleanup_cluster()` always terminates;
  - a slice fails as a unit, so "partially alive" clusters are treated as
    down and relaunched.
"""
from __future__ import annotations

import time
import typing
from typing import Any, Dict, Optional, Set

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu.jobs import constants
from skypilot_tpu.jobs import state as jobs_state

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backend import backend as backend_lib

logger = sky_logging.init_logger(__name__)

DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_REGION'

RECOVERY_STRATEGIES: Dict[str, type] = {}


class StrategyExecutor:
    """Handles launch / monitor-observed-failure / recover for one task
    (reference StrategyExecutor, recovery_strategy.py:46)."""

    NAME = '_ABSTRACT'

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 max_restarts_on_errors: int = 0) -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.max_restarts_on_errors = max_restarts_on_errors
        self.restart_cnt_on_failure = 0
        # Job id of the task's run in the cluster-side agent queue,
        # refreshed on every (re)launch; the controller polls it.
        self.job_id_on_cluster: Optional[int] = None
        # Set by the controller: checked between retries so a cancel can
        # interrupt an endless capacity-starved launch loop.
        self.should_abort: Optional[Any] = None

    def __init_subclass__(cls) -> None:
        if cls.NAME != '_ABSTRACT':
            RECOVERY_STRATEGIES[cls.NAME] = cls

    @classmethod
    def make(cls, cluster_name: str,
             task: 'task_lib.Task') -> 'StrategyExecutor':
        """Build the executor named by the task's `job_recovery` config
        (reference recovery_strategy.py:79 make)."""
        recovery: Dict[str, Any] = {}
        for r in task.get_preferred_resources():
            if r.job_recovery:
                recovery = dict(r.job_recovery)
                break
        name = recovery.pop('strategy', DEFAULT_RECOVERY_STRATEGY) or \
            DEFAULT_RECOVERY_STRATEGY
        if name not in RECOVERY_STRATEGIES:
            raise exceptions.ManagedJobStatusError(
                f'Unknown recovery strategy {name!r}; available: '
                f'{sorted(RECOVERY_STRATEGIES)}')
        max_restarts = int(recovery.pop('max_restarts_on_errors', 0))
        return RECOVERY_STRATEGIES[name](cluster_name, task,
                                         max_restarts_on_errors=max_restarts)

    # -- public API used by the controller ---------------------------------
    def launch(self) -> float:
        """First launch.  Returns the job start timestamp."""
        t = self._launch(max_retry=constants.launch_max_retry(),
                         raise_on_failure=True)
        assert t is not None
        return t

    def recover(self) -> float:
        """Relaunch after preemption/failure; returns new start timestamp.
        Subclasses implement placement policy."""
        raise NotImplementedError

    def should_restart_on_failure(self) -> bool:
        """User-code failure budget (reference recovery_strategy.py:229):
        consume one restart credit; False once exhausted."""
        self.restart_cnt_on_failure += 1
        return self.restart_cnt_on_failure <= self.max_restarts_on_errors

    def cleanup_cluster(self) -> None:
        """Terminate the task cluster (always terminate — TPU slices
        cannot be meaningfully stopped after preemption)."""
        try:
            core.down(self.cluster_name, purge=True)
        except (exceptions.ClusterDoesNotExist, exceptions.ClusterNotUpError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.warning(f'cleanup of {self.cluster_name} failed: {e}')

    # -- shared launch machinery -------------------------------------------
    def _launch(self, max_retry: Optional[int] = 3,
                raise_on_failure: bool = True,
                blocked_resources: Optional[
                    Set['resources_lib.Resources']] = None
                ) -> Optional[float]:
        """Launch with retries + backoff (reference _launch,
        recovery_strategy.py:239).  Returns job start time, or None if
        all retries exhausted and raise_on_failure=False."""
        backoff = constants.launch_retry_backoff_seconds()
        attempt = 0
        while True:
            attempt += 1
            if self.should_abort is not None and self.should_abort():
                raise exceptions.ManagedJobCancelledError(
                    f'Cancel requested while launching '
                    f'{self.cluster_name}.')
            try:
                # Detached run: the controller monitors via job status
                # polls, never holds a streaming connection.
                job_id, _ = execution.launch(
                    self.task,
                    cluster_name=self.cluster_name,
                    detach_run=True,
                    stream_logs=False,
                    quiet_optimizer=True,
                    blocked_resources=blocked_resources)
                self.job_id_on_cluster = job_id
                return time.time()
            except exceptions.ResourcesUnavailableError as e:
                logger.info(
                    f'Launch attempt {attempt} for {self.cluster_name} '
                    f'found no resources: {e}')
            except (exceptions.InvalidCloudCredentials,
                    exceptions.TaskValidationError,
                    exceptions.ResourcesValidationError) as e:
                # Precheck-class errors never heal by retrying.
                if raise_on_failure:
                    raise
                logger.warning(f'Precheck failure: {e}')
                return None
            except exceptions.CommandError as e:
                if e.command.startswith('setup on'):
                    # Setup scripts fail deterministically — a relaunch
                    # would run the same script again (the controller
                    # maps this to FAILED_SETUP).
                    self.cleanup_cluster()
                    raise
                logger.warning(
                    f'Launch attempt {attempt} for {self.cluster_name} '
                    f'failed running commands: {e}')
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    f'Launch attempt {attempt} for {self.cluster_name} '
                    f'failed: {e}')
            # Partially-provisioned cluster from the failed attempt must
            # not leak into the next attempt.
            self.cleanup_cluster()
            if max_retry is not None and attempt >= max_retry:
                if raise_on_failure:
                    raise exceptions.ManagedJobReachedMaxRetriesError(
                        f'Failed to launch {self.cluster_name} after '
                        f'{attempt} attempts.')
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)


class FailoverStrategyExecutor(StrategyExecutor):
    """Retry the same cloud/region first (capacity often returns in
    place), then fail over anywhere (reference recovery_strategy.py:388)."""

    NAME = 'FAILOVER'

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._last_launched: Optional['resources_lib.Resources'] = None

    def launch(self) -> float:
        t = super().launch()
        self._remember_launched()
        return t

    def _remember_launched(self) -> None:
        record = global_user_state.get_cluster_from_name(self.cluster_name)
        if record is not None:
            handle: 'backend_lib.ClusterHandle' = record['handle']
            self._last_launched = handle.launched_resources

    def recover(self) -> float:
        self.cleanup_cluster()
        # Step 1: pin to the previously-used region (one quick attempt).
        if self._last_launched is not None and \
                self._last_launched.region is not None:
            saved = list(self.task.get_preferred_resources())
            self.task.set_resources([
                r.copy(region=self._last_launched.region, zone=None)
                for r in saved
            ])
            try:
                t = self._launch(max_retry=1, raise_on_failure=False)
            finally:
                self.task.set_resources(saved)
            if t is not None:
                self._remember_launched()
                return t
        # Step 2: anywhere, forever (retry_until_up semantics).
        t = self._launch(max_retry=None, raise_on_failure=True)
        assert t is not None
        self._remember_launched()
        return t


class EagerNextRegionStrategyExecutor(StrategyExecutor):
    """Default: on preemption, blocklist the preempted region immediately
    and re-optimize elsewhere (reference recovery_strategy.py:471 — a
    just-preempted zone is the *worst* place to retry)."""

    NAME = 'EAGER_NEXT_REGION'

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._blocked: Set['resources_lib.Resources'] = set()

    def recover(self) -> float:
        from skypilot_tpu import resources as resources_lib
        record = global_user_state.get_cluster_from_name(self.cluster_name)
        if record is not None:
            handle: 'backend_lib.ClusterHandle' = record['handle']
            launched = handle.launched_resources
            if launched is not None and launched.region is not None:
                self._blocked.add(resources_lib.Resources(
                    cloud=launched.cloud, region=launched.region))
        self.cleanup_cluster()
        # First pass skips the preempted region; if the whole fleet is
        # starved, fall back to unconstrained retry-forever.
        t = self._launch(max_retry=constants.launch_max_retry(),
                         raise_on_failure=False,
                         blocked_resources=self._blocked or None)
        if t is not None:
            return t
        self._blocked.clear()
        t = self._launch(max_retry=None, raise_on_failure=True)
        assert t is not None
        return t
