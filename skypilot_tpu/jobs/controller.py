"""The managed-jobs controller: one monitor loop per job.

Counterpart of the reference's sky/jobs/controller.py: `JobsController`
(:50) and its `_run_one_task` recovery hot loop (:116) — every
`JOB_STATUS_CHECK_GAP` poll the task cluster's job status; SUCCEEDED →
clean up and advance the pipeline; cluster preempted/down → RECOVERING →
`strategy.recover()`; user-code failure → consume `max_restarts_on_errors`
credits or fail the job.

Deployment: by default the controller runs as a detached local process
(`python -m skypilot_tpu.jobs.controller --job-id N`) or an in-process
thread; for recovery that survives the client machine, jobs/remote.py
self-hosts this same loop on a controller *cluster* (the reference's
controller-VM deployment, sky/jobs/core.py:39).  All state is SQLite
(jobs/state.py), so a controller process can be restarted and resume
monitoring.
"""
from __future__ import annotations

import argparse
import threading
import time
import traceback
import typing
from typing import Optional

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu.backend import tpu_gang_backend
from skypilot_tpu.jobs import constants
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import dag_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

# Agent-side job statuses (skypilot_tpu/agent/job_lib.py JobStatus values).
_TERMINAL_OK = ('SUCCEEDED',)
_TERMINAL_USER_FAIL = ('FAILED',)
_TERMINAL_SETUP_FAIL = ('FAILED_SETUP',)
_TERMINAL_INFRA_FAIL = ('FAILED_DRIVER', 'CANCELLED')
# RPC failures tolerated before we treat the cluster as down even though
# the provider still reports it running.
_MAX_RPC_FAILURES = 3


class JobsController:
    """Monitors and recovers one managed job (possibly a pipeline)."""

    def __init__(self, job_id: int, dag: 'dag_lib.Dag') -> None:
        self._job_id = job_id
        self._dag = dag
        self._backend = tpu_gang_backend.TpuGangBackend()
        self._strategy: Optional[recovery_strategy.StrategyExecutor] = None

    # -- helpers -----------------------------------------------------------
    def _event(self, event: str, **kv) -> None:
        jobs_state.append_event(self._job_id, event, **kv)

    def _cluster_name(self, task_id: int) -> str:
        base = getattr(self._dag, 'name', None) or 'job'
        return (f'{constants.JOB_CLUSTER_NAME_PREFIX}'
                f'{common_utils.make_cluster_name_on_cloud(base, 20)}'
                f'-{self._job_id}-{task_id}')

    def _cluster_is_up(self, cluster_name: str) -> bool:
        """Cloud-truth liveness: refresh reconciles DB with the provider
        (preempted TPU slices disappear entirely — core.py refresh)."""
        try:
            record = core.refresh_cluster_record(cluster_name)
        except Exception:  # noqa: BLE001
            return False
        return (record is not None and
                record['status'] == global_user_state.ClusterStatus.UP)

    def _poll_job_status(self, cluster_name: str,
                         job_id_on_cluster: int) -> Optional[str]:
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is None:
            return None
        statuses = self._backend.get_job_status(record['handle'],
                                                [job_id_on_cluster])
        return statuses.get(job_id_on_cluster)

    # -- the hot loop ------------------------------------------------------
    def run(self) -> None:
        """Run all pipeline stages; record terminal state and release the
        scheduler slot no matter what (reference JobsController.run,
        controller.py:369)."""
        import networkx as nx
        try:
            order = list(nx.topological_sort(self._dag.get_graph()))
            for task_id, task in enumerate(order):
                if not self._run_one_task(task_id, task):
                    return
        except Exception as e:  # noqa: BLE001
            logger.error(f'Managed job {self._job_id} controller error: '
                         f'{e}\n{traceback.format_exc()}')
            jobs_state.set_failed(
                self._job_id, None,
                jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
                f'Controller crashed: {e}')
            if self._strategy is not None:
                self._strategy.cleanup_cluster()
        finally:
            scheduler.job_done(self._job_id)

    def _handle_cancel(self, task_id: int, cluster_name: str) -> None:
        self._event('cancelling', task_id=task_id)
        jobs_state.set_cancelling(self._job_id)
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is not None:
            try:
                self._backend.cancel_jobs(record['handle'], all_jobs=True)
            except Exception:  # noqa: BLE001
                pass
        if self._strategy is not None:
            self._strategy.cleanup_cluster()
        jobs_state.set_cancelled(self._job_id)
        jobs_state.clear_cancel(self._job_id)
        self._event('cancelled', task_id=task_id)

    def _run_one_task(self, task_id: int, task: 'task_lib.Task') -> bool:
        """Returns True iff the task SUCCEEDED (reference _run_one_task,
        controller.py:116)."""
        job_id = self._job_id
        cluster_name = self._cluster_name(task_id)
        strategy = recovery_strategy.StrategyExecutor.make(
            cluster_name, task)
        strategy.should_abort = \
            lambda: jobs_state.cancel_requested(job_id)
        self._strategy = strategy
        jobs_state.set_submitted(job_id, task_id, cluster_name)
        self._event('submitted', task_id=task_id, cluster=cluster_name)

        jobs_state.set_starting(job_id, task_id)
        try:
            with scheduler.scheduled_launch(job_id):
                start_time = strategy.launch()
        except exceptions.ManagedJobCancelledError:
            self._handle_cancel(task_id, cluster_name)
            return False
        except exceptions.ManagedJobReachedMaxRetriesError as e:
            jobs_state.set_failed(
                job_id, task_id,
                jobs_state.ManagedJobStatus.FAILED_NO_RESOURCE, str(e))
            return False
        except (exceptions.TaskValidationError,
                exceptions.ResourcesValidationError,
                exceptions.InvalidCloudCredentials) as e:
            jobs_state.set_failed(
                job_id, task_id,
                jobs_state.ManagedJobStatus.FAILED_PRECHECKS, str(e))
            return False
        except exceptions.CommandError as e:
            # Only setup failures propagate as CommandError out of the
            # strategy's launch loop (recovery_strategy._launch).
            jobs_state.set_failed(
                job_id, task_id,
                jobs_state.ManagedJobStatus.FAILED_SETUP,
                f'Setup failed: {e}')
            return False
        jobs_state.set_started(job_id, task_id, start_time)
        self._event('started', task_id=task_id)

        rpc_failures = 0
        gap = constants.job_status_check_gap_seconds()
        while True:
            if jobs_state.cancel_requested(job_id):
                self._handle_cancel(task_id, cluster_name)
                return False
            time.sleep(gap)

            status: Optional[str] = None
            rpc_ok = True
            try:
                assert strategy.job_id_on_cluster is not None
                status = self._poll_job_status(cluster_name,
                                               strategy.job_id_on_cluster)
            except Exception as e:  # noqa: BLE001
                rpc_ok = False
                logger.debug(f'Status poll failed for {cluster_name}: {e}')

            if status in _TERMINAL_OK:
                jobs_state.set_succeeded(job_id, task_id, time.time())
                self._event('succeeded', task_id=task_id)
                strategy.cleanup_cluster()
                return True

            if status in _TERMINAL_USER_FAIL:
                if strategy.should_restart_on_failure():
                    self._event('restart_on_failure', task_id=task_id,
                                attempt=strategy.restart_cnt_on_failure)
                    if self._recover(task_id, strategy) is None:
                        return False
                    rpc_failures = 0
                    continue
                jobs_state.set_failed(
                    job_id, task_id, jobs_state.ManagedJobStatus.FAILED,
                    'User program exited non-zero (restart budget '
                    'exhausted).')
                strategy.cleanup_cluster()
                return False

            if status in _TERMINAL_SETUP_FAIL:
                # Setup failures do not heal on relaunch (same setup
                # script would fail again) — reference fails fast here.
                jobs_state.set_failed(
                    job_id, task_id,
                    jobs_state.ManagedJobStatus.FAILED_SETUP,
                    'Setup script exited non-zero.')
                strategy.cleanup_cluster()
                return False

            if status in _TERMINAL_INFRA_FAIL:
                # Driver died / job cancelled out from under us: infra
                # fault → recover (reference treats non-user terminal as
                # recoverable).
                self._event('infra_failure', task_id=task_id,
                            status=status)
                if self._recover(task_id, strategy) is None:
                    return False
                rpc_failures = 0
                continue

            if status is not None:
                # PENDING / SETTING_UP / RUNNING — healthy.
                rpc_failures = 0
                continue

            # status is None: job missing or cluster unreachable (rpc_ok
            # distinguishes the two only for logging).
            del rpc_ok
            rpc_failures += 1
            if rpc_failures < _MAX_RPC_FAILURES and \
                    self._cluster_is_up(cluster_name):
                # Transient agent hiccup on a live cluster.
                continue
            # Cloud truth says down (or repeated failures): preemption.
            self._event('preemption_detected', task_id=task_id)
            if self._recover(task_id, strategy) is None:
                return False
            rpc_failures = 0

    def _recover(self, task_id: int,
                 strategy: recovery_strategy.StrategyExecutor
                 ) -> Optional[float]:
        """Returns the new start time, or None if a cancel interrupted
        the recovery (the job is then already CANCELLED)."""
        jobs_state.set_recovering(self._job_id, task_id)
        self._event('recovering', task_id=task_id)
        try:
            with scheduler.scheduled_launch(self._job_id):
                start_time = strategy.recover()
        except exceptions.ManagedJobCancelledError:
            self._handle_cancel(task_id, strategy.cluster_name)
            return None
        except exceptions.CommandError as e:
            jobs_state.set_failed(
                self._job_id, task_id,
                jobs_state.ManagedJobStatus.FAILED_SETUP,
                f'Setup failed during recovery: {e}')
            strategy.cleanup_cluster()
            return None
        jobs_state.set_recovered(self._job_id, task_id, start_time)
        self._event('recovered', task_id=task_id)
        return start_time


def run_controller(job_id: int) -> None:
    """Entry point: load the job's DAG and run the controller to
    completion (process mode target)."""
    info = jobs_state.get_job_info(job_id)
    if info is None:
        raise exceptions.ManagedJobStatusError(f'No managed job {job_id}.')
    dag = dag_utils.load_chain_dag_from_yaml(info['dag_yaml_path'])
    JobsController(job_id, dag).run()


_ACTIVE_THREADS: list = []


def start_controller_thread(job_id: int) -> threading.Thread:
    t = threading.Thread(target=run_controller, args=(job_id,),
                         name=f'jobs-controller-{job_id}', daemon=True)
    _ACTIVE_THREADS.append(t)
    t.start()
    return t


def join_all_controller_threads(timeout: float = 30.0) -> None:
    """Join thread-mode controllers (test teardown: prevents a lingering
    controller from writing into the next test's state dir)."""
    deadline = time.time() + timeout
    for t in list(_ACTIVE_THREADS):
        t.join(max(0.0, deadline - time.time()))
        if not t.is_alive():
            _ACTIVE_THREADS.remove(t)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    run_controller(args.job_id)


if __name__ == '__main__':
    main()
