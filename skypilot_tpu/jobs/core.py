"""Managed-jobs SDK: launch / queue / cancel / tail_logs.

Counterpart of the reference's sky/jobs/core.py (launch :39, queue,
cancel, tail_logs).  The reference ships the DAG to a controller VM via a
rendered `jobs-controller.yaml.j2` task; here the controller is a local
detached process (or thread — see jobs/controller.py module docstring),
so launch = persist DAG YAML + rows, then start the controller.
"""
from __future__ import annotations

import os
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import usage
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import controller as controller_lib
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import dag_utils
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)


@usage.entrypoint('sky.jobs.launch')
def launch(entrypoint: Union[task_lib.Task, dag_lib.Dag],
           name: Optional[str] = None,
           controller_mode: str = 'process') -> int:
    """Submit a managed job; returns its managed-job id immediately
    (recovery runs in the controller, not the caller).

    controller_mode: 'process' (default; detached, survives the caller),
    'thread' (daemon thread — hermetic tests), or 'inline' (block until
    the job reaches a terminal state).
    """
    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    dag.validate()
    if not dag.is_chain():
        raise exceptions.NotSupportedError(
            'Managed jobs support single tasks and chain pipelines only.')
    if name is not None:
        dag.name = name
    for t in dag.tasks:
        t.validate()

    dags_dir = os.path.join(jobs_state.jobs_dir(), 'dags')
    os.makedirs(dags_dir, exist_ok=True)
    dag_yaml_path = os.path.join(dags_dir, f'dag-{uuid.uuid4().hex}.yaml')
    dag_utils.dump_chain_dag_to_yaml(dag, dag_yaml_path)

    job_id = jobs_state.set_job_info(dag.name, dag_yaml_path)
    import networkx as nx
    order = list(nx.topological_sort(dag.get_graph()))
    for task_id, t in enumerate(order):
        rs = ', '.join(str(r) for r in t.get_preferred_resources())
        jobs_state.set_pending(job_id, task_id, t.name, rs)

    if controller_mode == 'process':
        log_path = jobs_state.controller_log_path(job_id)
        pid = subprocess_utils.launch_new_process_tree(
            f'{sys.executable} -m skypilot_tpu.jobs.controller '
            f'--job-id {job_id}', log_output=log_path + '.stderr')
        jobs_state.set_controller_pid(job_id, pid)
    elif controller_mode == 'thread':
        controller_lib.start_controller_thread(job_id)
    elif controller_mode == 'inline':
        controller_lib.run_controller(job_id)
    else:
        raise ValueError(f'Unknown controller_mode {controller_mode!r}')
    logger.info(f'Managed job {job_id} ({dag.name or "unnamed"}) '
                f'submitted ({controller_mode} controller).')
    return job_id


def queue(skip_finished: bool = False) -> List[Dict[str, Any]]:
    """All managed jobs, newest first (reference jobs/core.py queue)."""
    jobs = jobs_state.get_managed_jobs()
    if skip_finished:
        jobs = [j for j in jobs if not j['status'].is_terminal()]
    return jobs


def get_status(job_id: int) -> Optional[jobs_state.ManagedJobStatus]:
    return jobs_state.get_status(job_id)


def cancel(job_ids: Optional[List[int]] = None,
           name: Optional[str] = None,
           all_jobs: bool = False) -> List[int]:
    """Signal cancellation; the controller tears the task cluster down
    (reference jobs/core.py cancel)."""
    if all_jobs:
        job_ids = sorted({j['job_id'] for j in jobs_state.get_managed_jobs()
                          if not j['status'].is_terminal()})
    elif name is not None:
        job_ids = jobs_state.get_job_ids_by_name(name)
        if not job_ids:
            raise exceptions.ManagedJobStatusError(
                f'No managed job named {name!r}.')
    if not job_ids:
        return []
    cancelled = []
    for job_id in job_ids:
        st = jobs_state.get_status(job_id)
        if st is None or st.is_terminal():
            continue
        jobs_state.signal_cancel(job_id)
        cancelled.append(job_id)
    return cancelled


def wait(job_id: int, timeout: float = 300.0,
         poll_seconds: float = 0.5) -> jobs_state.ManagedJobStatus:
    """Block until the managed job reaches a terminal state (test/CI
    convenience; the reference exposes this only via `--follow` log
    streaming)."""
    deadline = time.time() + timeout
    while True:
        st = jobs_state.get_status(job_id)
        if st is not None and st.is_terminal():
            return st
        if time.time() > deadline:
            raise TimeoutError(
                f'Managed job {job_id} still {st} after {timeout}s.')
        time.sleep(poll_seconds)


def tail_logs(job_id: Optional[int] = None, name: Optional[str] = None,
              controller: bool = False, follow: bool = False) -> str:
    """Return the job's logs: controller event log (controller=True) or
    the task cluster's run log if the cluster is still up (streamed,
    optionally following, via core.tail_logs)."""
    if job_id is None:
        if name is None:
            raise ValueError('Provide job_id or name.')
        ids = jobs_state.get_job_ids_by_name(name)
        if not ids:
            raise exceptions.ManagedJobStatusError(
                f'No managed job named {name!r}.')
        job_id = ids[0]
    if controller:
        path = jobs_state.controller_log_path(job_id)
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                return f.read()
        return ''
    from skypilot_tpu import core as sky_core
    from skypilot_tpu import global_user_state
    for row in jobs_state.get_job_tasks(job_id):
        cluster = row['cluster_name']
        if cluster and global_user_state.get_cluster_from_name(cluster):
            sky_core.tail_logs(cluster, follow=follow)
            return ''
    return ''
