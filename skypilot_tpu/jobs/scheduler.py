"""Controller-wide scheduling of managed jobs.

Counterpart of the reference's sky/jobs/scheduler.py (283 LoC): caps the
number of concurrent cluster launches (launches are the expensive,
rate-limited phase) and of alive jobs, using a filelock around the
schedule-state column in the jobs DB (`maybe_schedule_next_jobs` :71,
`scheduled_launch` :184).  State machine per job:

    WAITING → LAUNCHING → ALIVE → (LAUNCHING ⇄ ALIVE on recoveries) → DONE
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator

import filelock

from skypilot_tpu import sky_logging
from skypilot_tpu.jobs import constants
from skypilot_tpu.jobs import state as jobs_state

logger = sky_logging.init_logger(__name__)


def _can_start_launch() -> bool:
    launching = jobs_state.count_schedule_states(
        [jobs_state.ScheduleState.LAUNCHING])
    alive = jobs_state.count_schedule_states(
        [jobs_state.ScheduleState.LAUNCHING, jobs_state.ScheduleState.ALIVE])
    return (launching < constants.max_concurrent_launches() and
            alive < constants.max_alive_jobs())


def wait_until_launchable(job_id: int, poll_seconds: float = 0.5,
                          timeout: float = 3600.0) -> None:
    """Block until this job may enter LAUNCHING, then claim the slot."""
    deadline = time.time() + timeout
    while True:
        try:
            with jobs_state.scheduler_lock():
                if _can_start_launch():
                    jobs_state.set_schedule_state(
                        job_id, jobs_state.ScheduleState.LAUNCHING)
                    return
        except filelock.Timeout:
            pass
        if time.time() > deadline:
            raise TimeoutError(
                f'Job {job_id} waited >{timeout}s for a launch slot.')
        time.sleep(poll_seconds)


@contextlib.contextmanager
def scheduled_launch(job_id: int) -> Iterator[None]:
    """Launch-slot guard (reference scheduled_launch, scheduler.py:184).
    On exit the job transitions LAUNCHING→ALIVE (success or not — a
    failed job is moved to DONE separately by job_done)."""
    wait_until_launchable(job_id)
    try:
        yield
    finally:
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.ALIVE)


def job_done(job_id: int) -> None:
    jobs_state.set_schedule_state(job_id, jobs_state.ScheduleState.DONE)
