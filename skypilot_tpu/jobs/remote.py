"""Self-hosted jobs controller: recovery that survives the client.

Reference semantics (sky/jobs/core.py:39 + jobs-controller.yaml.j2 +
wheel shipping, sky/backends/wheel_utils.py): `jobs launch` renders a
controller Task that file-mounts the user's DAG onto a dedicated
controller cluster and launches it there, so preemption recovery keeps
running after the client machine disappears.

Here the same shape, without the template layer:

  - a small, reusable controller cluster (default name
    `skytpu-jobs-controller`, resources from config
    `jobs.controller.resources`) is provisioned through the normal
    launch path — which ships the runtime tree and starts the agent;
  - the DAG YAML is file-mounted onto it and the submitted job runs
    `python -m skypilot_tpu.jobs.remote --dag <yaml>`: ON the
    controller host this registers the managed job in the host's own
    jobs DB and runs the controller inline, so the agent job stays
    RUNNING for the life of the managed job and its log is the
    controller event log;
  - client-side queue/cancel go through the same
    run-a-module-on-the-head channel the agent RPC uses (the
    reference's ManagedJobCodeGen-over-SSH analog, sky/jobs/utils.py).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import constants
from skypilot_tpu.utils import dag_utils

logger = sky_logging.init_logger(__name__)

_RESPONSE_BEGIN = '<skytpu-jobs-remote>'
_RESPONSE_END = '</skytpu-jobs-remote>'
# Relative to the job's cwd (<root>/<workdir>); file mounts land at the
# host root on every cloud (local: host dir, SSH: $HOME).
_DAG_MOUNT_DIR = 'managed_dags'


def controller_cluster_name() -> str:
    from skypilot_tpu import config
    return config.get_nested(('jobs', 'controller', 'cluster_name'),
                             'skytpu-jobs-controller')


def controller_resources() -> 'Any':
    """Controller cluster resources: config override or a small CPU VM
    (reference controller_utils.get_controller_resources)."""
    from skypilot_tpu import config
    from skypilot_tpu import resources as resources_lib
    spec = config.get_nested(('jobs', 'controller', 'resources'), None)
    if spec:
        return resources_lib.Resources.from_yaml_config(dict(spec))
    return resources_lib.Resources(cpus='2+')


def launch(entrypoint: Union[task_lib.Task, dag_lib.Dag],
           name: Optional[str] = None,
           controller_cluster: Optional[str] = None,
           resources: Optional[Any] = None) -> Tuple[str, int]:
    """Submit a managed job to the (auto-provisioned) controller
    cluster.  Returns (controller_cluster_name, agent_job_id) — the
    managed-job id is allocated on the controller host; query it with
    `queue()`.
    """
    from skypilot_tpu import execution

    import re
    import shlex
    import shutil

    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    dag.validate()
    if not dag.is_chain():
        raise exceptions.NotSupportedError(
            'Managed jobs support single tasks and chain pipelines only.')
    if name is not None:
        dag.name = name
    if dag.name is not None and not re.fullmatch(
            task_lib._VALID_NAME_REGEX, dag.name):  # pylint: disable=protected-access
        raise exceptions.TaskValidationError(
            f'Invalid managed-job name {dag.name!r}; must match '
            f'{task_lib._VALID_NAME_REGEX}')  # pylint: disable=protected-access
    for t in dag.tasks:
        t.validate()

    cluster = controller_cluster or controller_cluster_name()
    dag_basename = f'dag-{int(time.time())}-{uuid.uuid4().hex[:8]}.yaml'
    local_dir = tempfile.mkdtemp(prefix='skytpu-managed-')
    local_yaml = os.path.join(local_dir, dag_basename)
    dag_utils.dump_chain_dag_to_yaml(dag, local_yaml)

    job_name = dag.name or 'unnamed'
    controller_task = task_lib.Task(
        name=f'managed-{job_name}',
        run=(f'python3 -m skypilot_tpu.jobs.remote '
             f'--dag ../{_DAG_MOUNT_DIR}/{dag_basename} '
             f'--name {shlex.quote(job_name)}'),
    )
    controller_task.set_file_mounts(
        {f'{_DAG_MOUNT_DIR}/{dag_basename}': local_yaml})
    controller_task.set_resources(resources or controller_resources())

    try:
        job_id, _ = execution.launch(controller_task,
                                     cluster_name=cluster,
                                     detach_run=True,
                                     quiet_optimizer=True)
    finally:
        shutil.rmtree(local_dir, ignore_errors=True)
    logger.info(
        f'Managed job {job_name!r} submitted to controller cluster '
        f'{cluster!r} (agent job {job_id}). Recovery now runs there and '
        f'survives this client.')
    return cluster, job_id


# ---------------------------------------------------------------------------
# Client-side queries (run a module invocation on the controller head)
# ---------------------------------------------------------------------------
def _run_remote(controller_cluster: Optional[str],
                args: str) -> Dict[str, Any]:
    from skypilot_tpu.utils import controller_rpc
    cluster = controller_cluster or controller_cluster_name()
    return controller_rpc.call(cluster, 'skypilot_tpu.jobs.remote',
                               args, _RESPONSE_BEGIN, _RESPONSE_END)


def queue(controller_cluster: Optional[str] = None
          ) -> List[Dict[str, Any]]:
    """Managed jobs on the controller cluster, newest first."""
    return _run_remote(controller_cluster, '--queue-json')['jobs']


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False,
           controller_cluster: Optional[str] = None) -> List[int]:
    if all_jobs:
        args = '--cancel-all'
    elif job_ids:
        args = '--cancel ' + ' '.join(str(i) for i in job_ids)
    else:
        return []
    return _run_remote(controller_cluster, args)['cancelled']


def tail_logs(job_id: int, *,
              controller_cluster: Optional[str] = None) -> str:
    """The managed job's controller EVENT log, fetched from the
    controller host.  (Task run logs stream from the task cluster
    itself — `sky logs <task-cluster>` — not through this RPC: a
    framed response cannot carry a live stream.)"""
    args = f'--job-log {int(job_id)}'
    return _run_remote(controller_cluster, args)['log']


# ---------------------------------------------------------------------------
# Controller-host side (the file-mounted job's run command)
# ---------------------------------------------------------------------------
def _emit(payload: Dict[str, Any]) -> None:
    from skypilot_tpu.utils import controller_rpc
    controller_rpc.emit(payload, _RESPONSE_BEGIN, _RESPONSE_END)


def _serve_dag(dag_path: str, name: Optional[str]) -> None:
    """Register + run the managed job inline; the surrounding agent job
    is the controller process (its liveness IS controller liveness)."""
    from skypilot_tpu.jobs import core as jobs_core
    # Reference parity: the controller VM also serves the jobs dashboard
    # (systemd unit in jobs-controller.yaml.j2); here it rides the
    # controller process itself, reachable over SSH port-forwarding.
    dash_port = os.environ.get('SKYTPU_JOBS_DASHBOARD_PORT')
    if dash_port:
        try:
            from skypilot_tpu.jobs import dashboard
            dashboard.start(
                os.environ.get('SKYTPU_JOBS_DASHBOARD_HOST', '127.0.0.1'),
                int(dash_port))
        except (OSError, ValueError) as e:
            # Observability nicety must never fail the managed job
            # (e.g. EADDRINUSE when a concurrent controller already
            # serves the dashboard on this host).
            logger.warning(f'jobs dashboard not started: {e}')
    dag = dag_utils.load_chain_dag_from_yaml(os.path.expanduser(dag_path))
    job_id = jobs_core.launch(dag, name=name, controller_mode='inline')
    from skypilot_tpu.jobs import state as jobs_state
    status = jobs_state.get_status(job_id)
    logger.info('managed job %s finished: %s', job_id, status)
    if status is not jobs_state.ManagedJobStatus.SUCCEEDED:
        sys.exit(1)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--dag', default=None)
    parser.add_argument('--name', default=None)
    parser.add_argument('--queue-json', action='store_true')
    parser.add_argument('--job-log', type=int, default=None)
    parser.add_argument('--cancel', type=int, nargs='+', default=None)
    parser.add_argument('--cancel-all', action='store_true')
    args = parser.parse_args(argv)

    from skypilot_tpu.jobs import core as jobs_core
    if args.dag:
        _serve_dag(args.dag, args.name)
    elif args.queue_json:
        jobs = jobs_core.queue()
        for j in jobs:
            j['status'] = str(j['status'].value
                              if hasattr(j['status'], 'value')
                              else j['status'])
        _emit({'jobs': jobs})
    elif args.job_log is not None:
        log = jobs_core.tail_logs(args.job_log, controller=True)
        _emit({'log': log[-200_000:]})
    elif args.cancel or args.cancel_all:
        cancelled = jobs_core.cancel(job_ids=args.cancel,
                                     all_jobs=args.cancel_all)
        _emit({'cancelled': cancelled})
    else:
        parser.error('Nothing to do.')


if __name__ == '__main__':
    main()
