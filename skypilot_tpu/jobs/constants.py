"""Managed-jobs constants (reference: sky/jobs/constants.py + the polling
gaps hard-coded in sky/jobs/controller.py).  Env-overridable so hermetic
tests can run the recovery hot loop in milliseconds."""
from __future__ import annotations

import os


def _f(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, default))
    except ValueError:
        return default


def job_status_check_gap_seconds() -> float:
    """Poll gap of the controller's monitor loop (reference
    JOB_STATUS_CHECK_GAP_SECONDS = 20, sky/jobs/controller.py)."""
    return _f('SKYTPU_JOBS_STATUS_GAP', 20.0)


def launch_max_retry() -> int:
    return int(_f('SKYTPU_JOBS_LAUNCH_MAX_RETRY', 3))


def launch_retry_backoff_seconds() -> float:
    return _f('SKYTPU_JOBS_LAUNCH_BACKOFF', 5.0)


# Controller-wide parallelism caps (reference sky/jobs/scheduler.py:
# derived from controller VM size; here from the local host).
def max_concurrent_launches() -> int:
    return int(_f('SKYTPU_JOBS_MAX_LAUNCHES', 8))


def max_alive_jobs() -> int:
    return int(_f('SKYTPU_JOBS_MAX_ALIVE', 16))


JOB_CLUSTER_NAME_PREFIX = 'skytpu-job-'
