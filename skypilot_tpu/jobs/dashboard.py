"""Managed-jobs dashboard: a zero-dependency HTTP view of the job queue.

Counterpart of the reference's sky/jobs/dashboard/dashboard.py (a Flask
app + Jinja template served from the jobs controller, reached over SSH
port-forwarding via `sky jobs dashboard`, cli.py:3934).  Redesigned on
the stdlib: a ThreadingHTTPServer renders the same jobs table plus a
JSON API, so the dashboard works identically on a laptop, on a
self-hosted controller VM, or inside a test — no Flask, no template
directory to ship with the runtime rsync.

Routes:
  GET /              HTML page (auto-refreshing jobs table).
  GET /api/jobs      JSON list of (job, task) rows.
  GET /api/jobs/<id> JSON job detail: info + tasks + recent events.
  GET /healthz       liveness probe.
"""
from __future__ import annotations

import collections
import html
import http.server
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.jobs import state as jobs_state

logger = sky_logging.init_logger(__name__)

DEFAULT_PORT = 5050


def _jsonable(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, (jobs_state.ManagedJobStatus,
                          jobs_state.ScheduleState)):
            v = v.value
        out[k] = v
    return out


def jobs_snapshot() -> List[Dict[str, Any]]:
    return [_jsonable(r) for r in jobs_state.get_managed_jobs()]


def job_detail(job_id: int) -> Optional[Dict[str, Any]]:
    info = jobs_state.get_job_info(job_id)
    if info is None:
        return None
    events: List[Dict[str, Any]] = []
    try:
        with open(jobs_state.controller_log_path(job_id),
                  encoding='utf-8') as f:
            # deque streams the file; readlines() would hold a
            # recovery-churning job's whole event log in memory.
            for line in collections.deque(f, maxlen=200):
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    events.append({'raw': line.rstrip()})
    except OSError:
        pass
    return {
        'info': _jsonable(info),
        'tasks': [_jsonable(t) for t in jobs_state.get_job_tasks(job_id)],
        'events': events,
    }


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return '-'
    return time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))


def _fmt_dur(sec: Optional[float]) -> str:
    if sec is None:
        return '-'
    sec = int(sec)
    h, rem = divmod(sec, 3600)
    m, s = divmod(rem, 60)
    return f'{h}h {m}m {s}s' if h else (f'{m}m {s}s' if m else f'{s}s')


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>Managed jobs</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2em; color: #222; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #ddd; font-size: 14px; }}
 th {{ background: #f5f5f5; }}
 .SUCCEEDED {{ color: #1a7f37; }} .RUNNING {{ color: #0969da; }}
 .RECOVERING, .STARTING, .PENDING, .SUBMITTED {{ color: #9a6700; }}
 .FAILED, .FAILED_SETUP, .FAILED_PRECHECKS, .FAILED_NO_RESOURCE,
 .FAILED_CONTROLLER {{ color: #cf222e; }}
 .CANCELLED, .CANCELLING {{ color: #6e7781; }}
 #meta {{ color: #6e7781; font-size: 13px; margin-bottom: 1em; }}
</style></head>
<body>
<h2>Managed jobs</h2>
<div id="meta">auto-refreshing every 5s</div>
<table id="jobs"><thead><tr>
<th>ID</th><th>Task</th><th>Name</th><th>Resources</th><th>Submitted</th>
<th>Duration</th><th>Status</th><th>Cluster</th><th>#Recoveries</th>
<th>Failure</th></tr></thead><tbody>{rows}</tbody></table>
<script>
// All job fields are user-controlled (names, failure reasons): build
// cells with textContent, never innerHTML, to keep them inert.
function cell(text, cls) {{
  const td = document.createElement('td');
  td.textContent = text;
  if (cls) td.className = cls;
  return td;
}}
async function refresh() {{
  try {{
    const r = await fetch('/api/jobs');
    const jobs = await r.json();
    const tb = document.querySelector('#jobs tbody');
    tb.replaceChildren(...jobs.map(j => {{
      const tr = document.createElement('tr');
      tr.append(
        cell(j.job_id), cell(j.task_id),
        cell(j.job_name ?? j.task_name ?? '-'),
        cell(j.resources_str ?? '-'),
        cell(j.submitted_at ? new Date(j.submitted_at*1000)
             .toLocaleString() : '-'),
        cell(j.job_duration != null ? Math.round(j.job_duration)+'s'
             : '-'),
        cell(j.status, /^[A-Z_]+$/.test(j.status) ? j.status : ''),
        cell(j.cluster_name ?? '-'),
        cell(j.recovery_count ?? 0),
        cell(j.failure_reason ?? ''));
      return tr;
    }}));
    document.querySelector('#meta').textContent =
      jobs.length + ' jobs · refreshed ' + new Date().toLocaleTimeString();
  }} catch (e) {{ /* controller restarting; retry next tick */ }}
}}
refresh(); setInterval(refresh, 5000);
</script>
</body></html>
"""


def render_index() -> str:
    rows = []
    for j in jobs_snapshot():
        status = j['status']
        rows.append(
            '<tr>' + ''.join(
                f'<td{cls}>{html.escape(str(v))}</td>'
                for v, cls in [
                    (j['job_id'], ''), (j['task_id'], ''),
                    (j.get('job_name') or j.get('task_name') or '-', ''),
                    (j.get('resources_str') or '-', ''),
                    (_fmt_ts(j.get('submitted_at')), ''),
                    (_fmt_dur(j.get('job_duration')), ''),
                    (status, f' class="{status}"'),
                    (j.get('cluster_name') or '-', ''),
                    (j.get('recovery_count') or 0, ''),
                    (j.get('failure_reason') or '', ''),
                ]) + '</tr>')
    return _PAGE.format(rows=''.join(rows))


class _Handler(http.server.BaseHTTPRequestHandler):

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug('dashboard: ' + fmt % args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj: Any) -> None:
        self._send(code, json.dumps(obj).encode(), 'application/json')

    def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/':
                self._send(200, render_index().encode(), 'text/html')
            elif path == '/healthz':
                self._json(200, {'ok': True})
            elif path == '/api/jobs':
                self._json(200, jobs_snapshot())
            elif path.startswith('/api/jobs/'):
                try:
                    job_id = int(path.rsplit('/', 1)[1])
                except ValueError:
                    self._json(400, {'error': 'bad job id'})
                    return
                detail = job_detail(job_id)
                if detail is None:
                    self._json(404, {'error': f'no such job {job_id}'})
                else:
                    self._json(200, detail)
            else:
                self._json(404, {'error': 'not found'})
        except OSError:
            # Client went away mid-write (closed tab, aborted fetch):
            # not an error worth a traceback in the controller log.
            pass


def start(host: str = '127.0.0.1',
          port: int = DEFAULT_PORT
          ) -> Tuple[http.server.ThreadingHTTPServer, threading.Thread]:
    """Start the dashboard in a daemon thread; returns (server, thread).

    Callers own shutdown: `server.shutdown(); server.server_close()`.
    Pass port=0 to bind an ephemeral port (tests); the bound port is
    `server.server_address[1]`.
    """
    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name='jobs-dashboard', daemon=True)
    thread.start()
    logger.info('Jobs dashboard at http://%s:%d',
                host, server.server_address[1])
    return server, thread


def serve_forever(host: str = '127.0.0.1',
                  port: int = DEFAULT_PORT) -> None:
    server, thread = start(host, port)
    try:
        thread.join()
    finally:
        server.shutdown()
        server.server_close()
