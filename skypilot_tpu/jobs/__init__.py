"""Managed jobs: launch-and-forget tasks with automatic preemption
recovery (reference: sky/jobs/)."""
from skypilot_tpu.jobs.core import cancel
from skypilot_tpu.jobs.core import get_status
from skypilot_tpu.jobs.core import launch
from skypilot_tpu.jobs.core import queue
from skypilot_tpu.jobs.core import tail_logs
from skypilot_tpu.jobs.core import wait
from skypilot_tpu.jobs.state import ManagedJobStatus

__all__ = [
    'cancel', 'get_status', 'launch', 'queue', 'tail_logs', 'wait',
    'ManagedJobStatus',
]
