"""SSH keypair management (reference: sky/authentication.py:1-499).

One framework keypair under the state dir, injected into cloud instances
via provider metadata (GCP TPU-VM metadata ssh-keys — the reference's
TPU-VM special case).  Generated with ssh-keygen when available, else via
the `cryptography` library (minimal container images).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Tuple

import filelock

from skypilot_tpu.utils import paths

_KEY_NAME = 'skytpu-key'


def _generate_with_cryptography(private: str, public: str) -> None:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519
    key = ed25519.Ed25519PrivateKey.generate()
    priv_bytes = key.private_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PrivateFormat.OpenSSH,
        encryption_algorithm=serialization.NoEncryption())
    pub_bytes = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH)
    with open(private, 'wb') as f:
        f.write(priv_bytes)
    with open(public, 'wb') as f:
        f.write(pub_bytes + b' skytpu\n')


def get_or_generate_keys() -> Tuple[str, str]:
    """Return (private_key_path, public_key_path), generating once."""
    key_dir = paths.keys_dir()
    private = os.path.join(key_dir, _KEY_NAME)
    public = private + '.pub'
    with filelock.FileLock(private + '.lock'):
        if not (os.path.exists(private) and os.path.exists(public)):
            if shutil.which('ssh-keygen'):
                subprocess.run(
                    ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f',
                     private, '-C', 'skytpu'],
                    check=True, capture_output=True)
            else:
                _generate_with_cryptography(private, public)
        os.chmod(private, 0o600)
    return private, public
