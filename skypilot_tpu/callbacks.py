"""Step-timing callbacks feeding the benchmark harness.

Counterpart of the reference's separate `sky/callbacks/sky_callback`
pip package (SkyCallback step reporters for Keras/Lightning/HF feeding
a shared bucket — SURVEY.md §2.9).  Here the logger is part of the
framework: any training loop (ours or user code) calls
`BenchmarkLogger.maybe_from_env()` and `log_step()`; records land in a
JSONL file on the head node that `skypilot_tpu bench status` collects
via the agent RPC channel (no shared bucket required).

Env contract (injected by benchmark/harness.py):
    SKYTPU_BENCHMARK_LOG — absolute path of the JSONL step log.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

BENCHMARK_LOG_ENV = 'SKYTPU_BENCHMARK_LOG'


class BenchmarkLogger:
    """Appends {"step": n, "ts": unix_seconds} lines; one per step."""

    def __init__(self, path: str) -> None:
        self._path = os.path.expanduser(path)
        os.makedirs(os.path.dirname(self._path) or '.', exist_ok=True)
        self._fh = open(self._path, 'a', buffering=1)  # line-buffered

    @classmethod
    def maybe_from_env(cls) -> Optional['BenchmarkLogger']:
        path = os.environ.get(BENCHMARK_LOG_ENV)
        return cls(path) if path else None

    def log_step(self, step: int, **extra) -> None:
        rec = {'step': int(step), 'ts': time.time()}
        rec.update(extra)
        self._fh.write(json.dumps(rec) + '\n')

    def close(self) -> None:
        self._fh.close()


def log_step_from_env(step: int, **extra) -> None:
    """One-shot convenience for user scripts (opens/append/closes)."""
    path = os.environ.get(BENCHMARK_LOG_ENV)
    if not path:
        return
    logger = BenchmarkLogger(path)
    try:
        logger.log_step(step, **extra)
    finally:
        logger.close()
