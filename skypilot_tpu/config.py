"""Layered user configuration (~/.skytpu/config.yaml).

Counterpart of the reference's sky/skypilot_config.py:1-259: a nested dict
loaded once per process, `get_nested`/`set_nested` accessors over key
tuples, an env-var override for the config path, and a context manager to
substitute config for tests and controller processes (controllers receive a
serialized copy, reference sky/utils/controller_utils.py).
"""
from __future__ import annotations

import contextlib
import copy
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

ENV_VAR_CONFIG_PATH = 'SKYTPU_CONFIG'
CONFIG_PATH = '~/.skytpu/config.yaml'

_dict: Optional[Dict[str, Any]] = None
_loaded_config_path: Optional[str] = None
_lock = threading.Lock()


class Config(dict):
    """Nested-dict wrapper with tuple-keyed accessors."""

    def get_nested(self, keys: Tuple[str, ...], default_value: Any,
                   override_configs: Optional[Dict[str, Any]] = None) -> Any:
        config = copy.deepcopy(self)
        if override_configs:
            config = _recursive_update(config, override_configs)
        return _get_nested(config, keys, default_value)

    def set_nested(self, keys: Tuple[str, ...], value: Any) -> None:
        override = {}
        cursor = override
        for key in keys[:-1]:
            cursor[key] = {}
            cursor = cursor[key]
        cursor[keys[-1]] = value
        _recursive_update(self, override)


def _get_nested(config: Dict[str, Any], keys: Tuple[str, ...],
                default_value: Any) -> Any:
    cursor: Any = config
    for key in keys:
        if not isinstance(cursor, dict) or key not in cursor:
            return default_value
        cursor = cursor[key]
    return cursor


def _recursive_update(base: Dict[str, Any],
                      override: Dict[str, Any]) -> Dict[str, Any]:
    for key, value in override.items():
        if (isinstance(value, dict) and key in base and
                isinstance(base[key], dict)):
            _recursive_update(base[key], value)
        else:
            base[key] = value
    return base


def _try_load() -> None:
    global _dict, _loaded_config_path
    config_path = os.environ.get(ENV_VAR_CONFIG_PATH,
                                 os.path.expanduser(CONFIG_PATH))
    config_path = os.path.expanduser(config_path)
    if os.path.exists(config_path):
        try:
            with open(config_path, encoding='utf-8') as f:
                raw = yaml.safe_load(f) or {}
        except yaml.YAMLError as e:
            raise exceptions.InvalidSkyTpuConfigError(
                f'Failed to parse config at {config_path}: {e}') from e
        if not isinstance(raw, dict):
            raise exceptions.InvalidSkyTpuConfigError(
                f'Config at {config_path} must be a YAML mapping.')
        from skypilot_tpu.utils import schemas
        schemas.validate(raw, schemas.get_config_schema(),
                         exceptions.InvalidSkyTpuConfigError,
                         'Invalid config: ')
        _dict = Config(raw)
        _loaded_config_path = config_path
    else:
        _dict = Config()
        _loaded_config_path = None


def _ensure_loaded() -> Config:
    global _dict
    with _lock:
        if _dict is None:
            _try_load()
        assert _dict is not None
        return _dict  # type: ignore[return-value]


def loaded() -> bool:
    return bool(_ensure_loaded())


def loaded_config_path() -> Optional[str]:
    _ensure_loaded()
    return _loaded_config_path


def get_nested(keys: Tuple[str, ...], default_value: Any = None,
               override_configs: Optional[Dict[str, Any]] = None) -> Any:
    return _ensure_loaded().get_nested(keys, default_value, override_configs)


def set_nested(keys: Tuple[str, ...], value: Any) -> None:
    _ensure_loaded().set_nested(keys, value)


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(dict(_ensure_loaded()))


def reload() -> None:
    global _dict
    with _lock:
        _dict = None
    _ensure_loaded()


@contextlib.contextmanager
def replace_config(new_config: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Swap the process-wide config (tests, controllers)."""
    global _dict
    with _lock:
        old = _dict
        _dict = Config(new_config or {})
    try:
        yield
    finally:
        with _lock:
            _dict = old
