"""Function-per-operation provisioner dispatch.

Counterpart of the reference's sky/provision/__init__.py:32-227
(`_route_to_cloud_impl`): each cloud has a module
`skypilot_tpu.provision.<name>.instance` exporting the uniform interface:

    run_instances(region, cluster_name_on_cloud, config) -> ProvisionRecord
    stop_instances(cluster_name_on_cloud, provider_config, worker_only)
    terminate_instances(cluster_name_on_cloud, provider_config, worker_only)
    query_instances(cluster_name_on_cloud, provider_config,
                    non_terminated_only) -> Dict[instance_id, status|None]
    wait_instances(region, cluster_name_on_cloud, state)
    get_cluster_info(region, cluster_name_on_cloud, provider_config)
        -> ClusterInfo
    open_ports(cluster_name_on_cloud, ports, provider_config)
    cleanup_ports(cluster_name_on_cloud, ports, provider_config)
"""
from __future__ import annotations

import functools
import importlib
from typing import Any, Callable

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


@functools.lru_cache(maxsize=None)
def _get_cloud_module(provider_name: str):
    return importlib.import_module(
        f'skypilot_tpu.provision.{provider_name.lower()}.instance')


def _route(fn_name: str) -> Callable:
    def impl(provider_name: str, *args: Any, **kwargs: Any) -> Any:
        module = _get_cloud_module(provider_name)
        fn = getattr(module, fn_name, None)
        if fn is None:
            raise NotImplementedError(
                f'Provisioner {provider_name!r} does not implement '
                f'{fn_name}.')
        return fn(*args, **kwargs)

    impl.__name__ = fn_name
    return impl


def query_ports(provider_name: str, cluster_name_on_cloud: str,
                ports, head_ip=None, provider_config=None):
    """Endpoint URLs for opened ports (reference
    sky/provision/__init__.py query_ports): clouds that expose ports
    on the head's public IP fall back to the passthrough; clouds with
    an indirection layer (kubernetes LB/NodePort services) implement
    their own."""
    module = _get_cloud_module(provider_name)
    fn = getattr(module, 'query_ports', None)
    if fn is not None:
        return fn(cluster_name_on_cloud, ports, provider_config)
    if head_ip is None:
        return {}
    from skypilot_tpu.provision import common
    return common.query_ports_passthrough(ports, head_ip)


run_instances = _route('run_instances')
stop_instances = _route('stop_instances')
terminate_instances = _route('terminate_instances')
query_instances = _route('query_instances')
wait_instances = _route('wait_instances')
get_cluster_info = _route('get_cluster_info')
open_ports = _route('open_ports')
cleanup_ports = _route('cleanup_ports')
