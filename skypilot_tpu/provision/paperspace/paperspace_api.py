"""Minimal Paperspace REST client (JSON over urllib).

Counterpart of the reference's sky/provision/paperspace/utils.py
(requests-based PaperspaceCloudClient) against the same API:
https://api.paperspace.com/v1 with Bearer API-key auth.  Key from env
PAPERSPACE_API_KEY or ~/.paperspace/config.json ({"apiKey": ...}).
All calls route through `request`, the single test seam.
"""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ROOT = 'https://api.paperspace.com/v1'
_TIMEOUT = 60.0
_CONFIG_FILE = '~/.paperspace/config.json'


class PaperspaceApiError(exceptions.ProvisionError):

    def __init__(self, status_code: int, code: str, message: str) -> None:
        no_failover = status_code in (401, 403)
        super().__init__(
            f'Paperspace API error {status_code} {code}: {message}',
            no_failover=no_failover)
        self.status_code = status_code
        self.code = code


def load_api_key() -> Optional[str]:
    key = os.environ.get('PAPERSPACE_API_KEY')
    if key:
        return key
    path = os.path.expanduser(
        os.environ.get('PAPERSPACE_CONFIG_FILE', _CONFIG_FILE))
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return json.load(f).get('apiKey')
    except (OSError, json.JSONDecodeError):
        return None


def request(method: str, path: str,
            body: Optional[Dict[str, Any]] = None,
            params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    key = load_api_key()
    if key is None:
        raise PaperspaceApiError(401, 'NoCredentials',
                                 'no Paperspace API key')
    url = f'{API_ROOT}{path}'
    if params:
        url += '?' + urllib.parse.urlencode(params)
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={'Authorization': f'Bearer {key}',
                 'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=_TIMEOUT) as resp:
            text = resp.read()
            return json.loads(text) if text.strip() else {}
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors='replace')
        try:
            msg = str(json.loads(text).get('message', text[:200]))
        except json.JSONDecodeError:
            msg = text[:200]
        code = ('insufficient-capacity'
                if 'out of stock' in msg.lower() or
                'capacity' in msg.lower() else 'unknown')
        raise PaperspaceApiError(e.code, code, msg) from None
    except urllib.error.URLError as e:
        raise PaperspaceApiError(0, 'Unreachable', str(e)) from None


def list_machines(name: Optional[str] = None) -> List[Dict[str, Any]]:
    params = {'limit': '100'}
    if name:
        params['name'] = name
    return list(request('GET', '/machines', params=params)
                .get('items') or [])


def create_machine(name: str, machine_type: str, region: str,
                   disk_size_gb: int,
                   startup_script: Optional[str] = None
                   ) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        'name': name,
        'machineType': machine_type,
        'templateId': 't0nspur5',  # Ubuntu 22.04 ML-in-a-Box
        'region': region,
        'diskSize': disk_size_gb,
        'publicIpType': 'dynamic',
        'startOnCreate': True,
    }
    if startup_script:
        body['startupScript'] = startup_script
    return dict(request('POST', '/machines', body)
                .get('data') or {})


def machine_action(machine_id: str, action: str) -> None:
    """start | stop."""
    request('PATCH' if action == 'rename' else 'POST',
            f'/machines/{machine_id}/{action}')


def delete_machine(machine_id: str) -> None:
    try:
        request('DELETE', f'/machines/{machine_id}')
    except PaperspaceApiError as e:
        if e.status_code != 404:
            raise
