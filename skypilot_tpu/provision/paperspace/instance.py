"""Paperspace provisioner: the uniform provision interface.

Counterpart of the reference's sky/provision/paperspace/instance.py.
Machines are named `<cluster>-<idx>`, support stop/start, and get the
framework SSH key via a startup script (the reference registers a
startup script the same way).
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.paperspace import paperspace_api

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'paperspace'


def _classify(e: paperspace_api.PaperspaceApiError) -> Exception:
    if e.code == 'insufficient-capacity':
        return exceptions.ResourcesUnavailableError(str(e))
    return e


def _cluster_machines(cluster_name_on_cloud: str
                      ) -> List[Dict[str, Any]]:
    pattern = re.compile(
        rf'^{re.escape(cluster_name_on_cloud)}-\d{{4}}$')
    return sorted(
        (m for m in paperspace_api.list_machines()
         if pattern.fullmatch(str(m.get('name', '')))),
        key=lambda m: str(m.get('name')))


def _ssh_startup_script(auth_config: Dict[str, Any]) -> Optional[str]:
    ssh_keys = (auth_config or {}).get('ssh_keys', '')
    if ':' not in ssh_keys:
        return None
    pub = ssh_keys.split(':', 1)[1]
    return ('#!/bin/bash\n'
            'mkdir -p /home/paperspace/.ssh\n'
            f'echo {pub!r} >> /home/paperspace/.ssh/authorized_keys\n'
            'chown -R paperspace:paperspace /home/paperspace/.ssh\n'
            'chmod 600 /home/paperspace/.ssh/authorized_keys\n')


def _state(machine: Dict[str, Any]) -> str:
    return str(machine.get('state', 'unknown'))


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    try:
        existing = _cluster_machines(cluster_name_on_cloud)
        running = [m for m in existing
                   if _state(m) in ('ready', 'starting',
                                    'provisioning')]
        stopped = [m for m in existing if _state(m) == 'off']

        resumed: List[str] = []
        if config.resume_stopped_nodes and stopped:
            need = config.count - len(running)
            for m in stopped[:max(need, 0)]:
                paperspace_api.machine_action(str(m['id']), 'start')
                resumed.append(str(m['id']))
            running += [m for m in stopped
                        if str(m['id']) in resumed]

        created: List[str] = []
        to_create = config.count - len(running)
        if to_create > 0:
            script = _ssh_startup_script(config.authentication_config)
            base = len(existing)
            for i in range(to_create):
                machine = paperspace_api.create_machine(
                    name=f'{cluster_name_on_cloud}-{base + i:04d}',
                    machine_type=node_cfg['instance_type'],
                    region=region,
                    disk_size_gb=int(node_cfg.get('disk_size') or 100),
                    startup_script=script)
                created.append(str(machine.get('id')))
    except paperspace_api.PaperspaceApiError as e:
        raise _classify(e) from None
    ids = sorted([str(m['id']) for m in running] + created)
    if not ids:
        raise exceptions.ResourcesUnavailableError(
            f'Paperspace returned no machines for '
            f'{cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER, cluster_name=cluster_name_on_cloud,
        region=region, zone=None, head_instance_id=ids[0],
        resumed_instance_ids=resumed, created_instance_ids=created)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    machines = [m for m in _cluster_machines(cluster_name_on_cloud)
                if _state(m) in ('ready', 'starting', 'provisioning')]
    ids = sorted(str(m['id']) for m in machines)
    if worker_only and ids:
        ids = ids[1:]
    for mid in ids:
        paperspace_api.machine_action(mid, 'stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    ids = sorted(str(m['id'])
                 for m in _cluster_machines(cluster_name_on_cloud))
    if worker_only and ids:
        ids = ids[1:]
    for mid in ids:
        paperspace_api.delete_machine(mid)


_STATUS_MAP = {
    'provisioning': 'pending',
    'starting': 'pending',
    'restarting': 'pending',
    'ready': 'running',
    'stopping': 'stopping',
    'off': 'stopped',
    'upgrading': 'pending',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for m in _cluster_machines(cluster_name_on_cloud):
        status = _STATUS_MAP.get(_state(m))
        if non_terminated_only and status == 'terminated':
            continue
        out[str(m['id'])] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: str = 'running', timeout: float = 900.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud, None,
                                   non_terminated_only=False)
        live = [s for s in statuses.values() if s != 'terminated']
        if live and all(s == state for s in live):
            return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'{cluster_name_on_cloud}: machines did not reach {state!r} '
        f'within {timeout}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for m in _cluster_machines(cluster_name_on_cloud):
        if _state(m) != 'ready':
            continue
        mid = str(m['id'])
        instances[mid] = [common.InstanceInfo(
            instance_id=mid,
            internal_ip=str(m.get('privateIp') or ''),
            external_ip=m.get('publicIp'),
            tags={'name': str(m.get('name'))},
        )]
    head = sorted(instances)[0] if instances else None
    return common.ClusterInfo(
        instances=instances, head_instance_id=head,
        provider_name=_PROVIDER, provider_config=provider_config,
        ssh_user='paperspace')


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.info('Paperspace machines expose a public IP with no '
                'managed firewall; ports %s are reachable.', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('Paperspace has no managed firewall; nothing to close for %s.', ports)
