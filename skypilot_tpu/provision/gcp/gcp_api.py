"""Minimal authenticated REST client for GCP (TPU + Compute + networking).

The reference drives GCP through google-api-python-client discovery
services (sky/adaptors/gcp.py, sky/provision/gcp/instance_utils.py:
1203-1210 builds the `tpu` discovery service).  That SDK is not available
here, so this module is a small, dependency-light REST layer over
`requests` with google.auth ADC tokens — same API surface
(tpu.googleapis.com/v2, compute.googleapis.com/compute/v1).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

TPU_API = 'https://tpu.googleapis.com/v2'
COMPUTE_API = 'https://compute.googleapis.com/compute/v1'

_SCOPES = ['https://www.googleapis.com/auth/cloud-platform']


class GcpApiError(exceptions.ProvisionError):
    """HTTP-level error from a GCP API; carries status + parsed body."""

    def __init__(self, status_code: int, message: str,
                 body: Optional[Dict[str, Any]] = None) -> None:
        no_failover = status_code in (401, 403)  # credential problems
        super().__init__(f'GCP API error {status_code}: {message}',
                         no_failover=no_failover)
        self.status_code = status_code
        self.body = body or {}

    @property
    def reason(self) -> str:
        errors = self.body.get('error', {}).get('errors', [])
        if errors:
            return errors[0].get('reason', '')
        return self.body.get('error', {}).get('status', '')


class _Session:

    def __init__(self) -> None:
        import google.auth
        import google.auth.transport.requests
        import requests
        self._requests = requests
        self._credentials, self.project = google.auth.default(scopes=_SCOPES)
        self._auth_request = google.auth.transport.requests.Request()
        self._http = requests.Session()

    def _headers(self) -> Dict[str, str]:
        if not self._credentials.valid:
            self._credentials.refresh(self._auth_request)
        return {
            'Authorization': f'Bearer {self._credentials.token}',
            'Content-Type': 'application/json',
        }

    def request(self, method: str, url: str,
                json_body: Optional[Dict[str, Any]] = None,
                params: Optional[Dict[str, str]] = None,
                retries: int = 3) -> Dict[str, Any]:
        last_err: Optional[Exception] = None
        for attempt in range(retries):
            try:
                resp = self._http.request(method, url, json=json_body,
                                          params=params,
                                          headers=self._headers(),
                                          timeout=60)
            except self._requests.RequestException as e:
                last_err = e
                time.sleep(2 ** attempt)
                continue
            if resp.status_code == 200:
                return resp.json() if resp.content else {}
            if resp.status_code in (429, 500, 502, 503) and \
                    attempt < retries - 1:
                time.sleep(2 ** attempt)
                continue
            try:
                body = resp.json()
            except ValueError:
                body = {'error': {'message': resp.text[:500]}}
            message = body.get('error', {}).get('message', resp.text[:500])
            raise GcpApiError(resp.status_code, message, body)
        raise exceptions.ProvisionError(
            f'GCP API request failed after {retries} retries: {last_err}')


@functools.lru_cache(maxsize=1)
def session() -> _Session:
    return _Session()


def default_project() -> str:
    proj = session().project
    if not proj:
        from skypilot_tpu import config as config_lib
        proj = config_lib.get_nested(('gcp', 'project_id'), None)
    if not proj:
        raise exceptions.InvalidCloudCredentials(
            'No GCP project configured. Set gcp.project_id in '
            '~/.skytpu/config.yaml or use application-default credentials '
            'with a project.')
    return proj


# ---------------------------------------------------------------------------
# TPU API (tpu.googleapis.com/v2) — TPU-VM nodes
# ---------------------------------------------------------------------------
def tpu_parent(project: str, zone: str) -> str:
    return f'projects/{project}/locations/{zone}'


def create_tpu_node(project: str, zone: str, node_id: str,
                    node_body: Dict[str, Any]) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes'
    return session().request('POST', url, json_body=node_body,
                             params={'nodeId': node_id})


def get_tpu_node(project: str, zone: str,
                 node_id: str) -> Optional[Dict[str, Any]]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes/{node_id}'
    try:
        return session().request('GET', url)
    except GcpApiError as e:
        if e.status_code == 404:
            return None
        raise


def list_tpu_nodes(project: str, zone: str) -> List[Dict[str, Any]]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes'
    nodes: List[Dict[str, Any]] = []
    page_token: Optional[str] = None
    while True:
        params = {'pageToken': page_token} if page_token else None
        resp = session().request('GET', url, params=params)
        nodes.extend(resp.get('nodes', []))
        page_token = resp.get('nextPageToken')
        if not page_token:
            return nodes


def delete_tpu_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes/{node_id}'
    return session().request('DELETE', url)


def stop_tpu_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes/{node_id}:stop'
    return session().request('POST', url, json_body={})


def start_tpu_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/nodes/{node_id}:start'
    return session().request('POST', url, json_body={})


def wait_tpu_operation(operation: Dict[str, Any],
                       timeout_s: float = 1800) -> Dict[str, Any]:
    """Poll a TPU longrunning operation until done (reference:
    instance_utils.py:1212 TPU op polling)."""
    name = operation.get('name')
    if name is None or operation.get('done'):
        return operation
    url = f'{TPU_API}/{name}'
    deadline = time.time() + timeout_s
    interval = 5.0
    while time.time() < deadline:
        op = session().request('GET', url)
        if op.get('done'):
            if 'error' in op:
                err = op['error']
                raise exceptions.ProvisionError(
                    f'TPU operation failed: {err.get("message", err)}')
            return op
        time.sleep(interval)
        interval = min(interval * 1.3, 20.0)
    raise exceptions.ProvisionTimeoutError(
        f'TPU operation {name} did not complete in {timeout_s}s.')


# ---------------------------------------------------------------------------
# Queued resources (multislice / DWS-style queued TPU capacity)
# ---------------------------------------------------------------------------
def create_queued_resource(project: str, zone: str, qr_id: str,
                           body: Dict[str, Any]) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/queuedResources'
    return session().request('POST', url, json_body=body,
                             params={'queuedResourceId': qr_id})


def get_queued_resource(project: str, zone: str,
                        qr_id: str) -> Optional[Dict[str, Any]]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/queuedResources/{qr_id}'
    try:
        return session().request('GET', url)
    except GcpApiError as e:
        if e.status_code == 404:
            return None
        raise


def delete_queued_resource(project: str, zone: str,
                           qr_id: str) -> Dict[str, Any]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/queuedResources/{qr_id}'
    return session().request('DELETE', url, params={'force': 'true'})


def list_queued_resources(project: str,
                          zone: str) -> List[Dict[str, Any]]:
    url = f'{TPU_API}/{tpu_parent(project, zone)}/queuedResources'
    out: List[Dict[str, Any]] = []
    params: Optional[Dict[str, Any]] = None
    while True:
        resp = session().request('GET', url, params=params)
        out.extend(resp.get('queuedResources', []))
        token = resp.get('nextPageToken')
        if not token:
            return out
        params = {'pageToken': token}


# ---------------------------------------------------------------------------
# Compute API — controller VMs + firewall
# ---------------------------------------------------------------------------
def insert_instance(project: str, zone: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
    url = f'{COMPUTE_API}/projects/{project}/zones/{zone}/instances'
    return session().request('POST', url, json_body=body)


def get_instance(project: str, zone: str,
                 name: str) -> Optional[Dict[str, Any]]:
    url = f'{COMPUTE_API}/projects/{project}/zones/{zone}/instances/{name}'
    try:
        return session().request('GET', url)
    except GcpApiError as e:
        if e.status_code == 404:
            return None
        raise


def list_instances(project: str, zone: str,
                   label_filter: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    url = f'{COMPUTE_API}/projects/{project}/zones/{zone}/instances'
    params = {'filter': label_filter} if label_filter else None
    out: List[Dict[str, Any]] = []
    while True:
        resp = session().request('GET', url, params=params)
        out.extend(resp.get('items', []))
        token = resp.get('nextPageToken')
        if not token:
            return out
        params = dict(params or {})
        params['pageToken'] = token


def instance_action(project: str, zone: str, name: str,
                    action: str) -> Dict[str, Any]:
    url = (f'{COMPUTE_API}/projects/{project}/zones/{zone}/instances/'
           f'{name}/{action}')
    return session().request('POST', url, json_body={})


def delete_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    url = f'{COMPUTE_API}/projects/{project}/zones/{zone}/instances/{name}'
    return session().request('DELETE', url)


def wait_zone_operation(project: str, zone: str, operation: Dict[str, Any],
                        timeout_s: float = 600) -> None:
    name = operation.get('name')
    if name is None:
        return
    url = (f'{COMPUTE_API}/projects/{project}/zones/{zone}/operations/'
           f'{name}/wait')
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        op = session().request('POST', url, json_body={})
        if op.get('status') == 'DONE':
            if 'error' in op:
                errors = op['error'].get('errors', [])
                msg = '; '.join(e.get('message', '') for e in errors)
                raise exceptions.ProvisionError(
                    f'Compute operation failed: {msg}')
            return
    raise exceptions.ProvisionTimeoutError(
        f'Compute operation {name} timed out after {timeout_s}s.')


def insert_firewall_rule(project: str, body: Dict[str, Any]
                         ) -> Dict[str, Any]:
    url = f'{COMPUTE_API}/projects/{project}/global/firewalls'
    return session().request('POST', url, json_body=body)


def delete_firewall_rule(project: str, name: str) -> Dict[str, Any]:
    url = f'{COMPUTE_API}/projects/{project}/global/firewalls/{name}'
    return session().request('DELETE', url)
