"""GCP provisioner: TPU-VM slices (tpu.googleapis.com/v2) + GCE VMs.

Counterpart of the reference's sky/provision/gcp/instance_utils.py —
specifically `GCPTPUVMInstance` (:1191, discovery-API based) and
`GCPComputeInstance` (:311) — rebuilt slice-first on the REST layer in
gcp_api.py:

  - A *TPU slice* is one logical instance: a single TPU node resource whose
    networkEndpoints list all host VMs.  Creation/deletion is atomic at the
    API level, which is exactly the gang-admission property the reference
    emulates with Ray placement groups (cloud_vm_ray_backend.py:450-456).
  - Preempted/failed slices are DELETED, never stopped
    (resources.py:633 semantics); single-host non-pod TPU VMs may stop.
  - Capacity/quota errors are classified into failover-able
    ProvisionError vs terminal no_failover errors, the TPU analog of the
    reference's GCP error parser (cloud_vm_ray_backend.py:967-1070).
  - SSH keys are injected through node metadata (authentication.py TPU-VM
    special case in the reference).
"""
from __future__ import annotations

import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import gcp_api

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'gcp'
_LABEL_CLUSTER = 'skytpu-cluster'

# Messages that indicate lack of capacity → failover to next zone
# (reference: FailoverCloudErrorHandlerV2 GCP parser incl. the TPU
# capacity message, cloud_vm_ray_backend.py:1036).
_CAPACITY_PATTERNS = [
    r'There is no more capacity in the zone',
    r'Not enough resources available to fulfill the request',
    r'ZONE_RESOURCE_POOL_EXHAUSTED',
    r'RESOURCE_EXHAUSTED',
    r'stockout',
    r'The zone .* does not have enough resources',
]
_QUOTA_PATTERNS = [
    r'Quota exceeded for quota metric',
    r'QUOTA_EXCEEDED',
    r"quota '.*' exceeded",
]


def _classify_api_error(e: gcp_api.GcpApiError) -> Exception:
    msg = str(e)
    for pat in _CAPACITY_PATTERNS:
        if re.search(pat, msg, re.IGNORECASE):
            return exceptions.ProvisionError(
                f'GCP capacity unavailable: {msg}', no_failover=False)
    for pat in _QUOTA_PATTERNS:
        if re.search(pat, msg, re.IGNORECASE):
            # Quota is per-region: failover to other regions can still help,
            # but retrying the same zone cannot.
            return exceptions.ProvisionError(f'GCP quota exceeded: {msg}',
                                             no_failover=False)
    if e.status_code in (401, 403):
        return exceptions.ProvisionError(
            f'GCP permission error (no failover): {msg}', no_failover=True)
    if e.status_code == 409:
        return exceptions.ProvisionError(f'GCP conflict: {msg}',
                                         no_failover=False)
    return e


def _project(provider_config: Optional[Dict[str, Any]]) -> str:
    if provider_config and provider_config.get('project_id'):
        return provider_config['project_id']
    return gcp_api.default_project()


def _is_tpu_config(node_config: Dict[str, Any]) -> bool:
    return bool(node_config.get('tpu_vm'))


# ---------------------------------------------------------------------------
# run_instances
# ---------------------------------------------------------------------------
def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    zone = node_cfg['zone']
    project = _project(config.provider_config)
    try:
        if _is_tpu_config(node_cfg):
            return _run_tpu_slices(project, region, zone,
                                   cluster_name_on_cloud, config)
        return _run_gce_instances(project, region, zone,
                                  cluster_name_on_cloud, config)
    except gcp_api.GcpApiError as e:
        raise _classify_api_error(e) from e


def _node_name(cluster_name_on_cloud: str, idx: int) -> str:
    return f'{cluster_name_on_cloud}-{idx}'


def _fresh_node_names(cluster_name_on_cloud: str, taken: set,
                      count: int) -> List[str]:
    """Names not colliding with live OR deleted-but-listed nodes (a
    preempted node's index must not be reused while its record lingers)."""
    out: List[str] = []
    idx = 0
    while len(out) < count:
        name = _node_name(cluster_name_on_cloud, idx)
        if name not in taken:
            out.append(name)
        idx += 1
    return out


def _tpu_node_body(node_cfg: Dict[str, Any], cluster_name_on_cloud: str,
                   config: common.ProvisionConfig) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        'acceleratorType': node_cfg['tpu_type'],
        'runtimeVersion': node_cfg['runtime_version'],
        'networkConfig': {'enableExternalIps': True},
        'labels': {
            _LABEL_CLUSTER: cluster_name_on_cloud,
            **{k.lower(): str(v).lower()
               for k, v in config.tags.items()},
        },
        'metadata': {
            'ssh-keys': config.authentication_config.get('ssh_keys', ''),
            'startup-script':
                config.authentication_config.get('startup_script', ''),
        },
        'schedulingConfig': {
            'preemptible': bool(node_cfg.get('use_spot')),
        },
    }
    if node_cfg.get('tpu_topology'):
        # TPU API AcceleratorConfig enum names.
        accel_type = {
            'v2': 'V2', 'v3': 'V3', 'v4': 'V4',
            'v5e': 'V5LITE_POD', 'v5p': 'V5P', 'v6e': 'V6E',
        }[node_cfg['tpu_generation']]
        body['acceleratorConfig'] = {
            'type': accel_type,
            'topology': node_cfg['tpu_topology'],
        }
        body.pop('acceleratorType')
    if node_cfg.get('reservation'):
        body['schedulingConfig']['reserved'] = True
    return body


def _queued_timeout_s() -> float:
    try:
        return float(os.environ.get('SKYTPU_QUEUED_TIMEOUT', '1800'))
    except ValueError:
        return 1800.0


def _qr_id(node_id: str) -> str:
    return f'{node_id}-qr'


def _create_via_queued_resource(project: str, zone: str,
                                node_ids: List[str],
                                node_bodies: List[Dict[str, Any]],
                                node_cfg: Dict[str, Any]) -> None:
    """Create ALL requested TPU slices through ONE queuedResource and
    wait for ACTIVE (reference analog: DWS/MIG machinery,
    sky/provision/gcp/instance_utils.py:978 + mig_utils.py — the
    real-world way to obtain v5p/v6e capacity).

    A single multi-nodeSpec request gives gang admission at the
    capacity level: all slices are allocated together or the request
    fails as a unit, and there is one wait instead of N serialized
    timeouts.  State machine: ACCEPTED → PROVISIONING → ACTIVE;
    FAILED / SUSPENDED, timeout, a vanished request, or ANY abnormal
    exit (including interruption) deletes the request so nothing leaks
    and a retry can reuse the id.
    """
    qr_id = _qr_id(node_ids[0])
    parent = gcp_api.tpu_parent(project, zone)
    node_specs = []
    for node_id, node_body in zip(node_ids, node_bodies):
        # Node bodies inside a QR must not carry schedulingConfig; the
        # tier (spot/guaranteed) is expressed on the QR itself.
        spec_body = dict(node_body)
        spec_body.pop('schedulingConfig', None)
        node_specs.append({'parent': parent, 'nodeId': node_id,
                           'node': spec_body})
    qr_body: Dict[str, Any] = {'tpu': {'nodeSpec': node_specs}}
    reservation = node_cfg.get('reservation')
    if node_cfg.get('use_spot'):
        qr_body['spot'] = {}
    elif reservation:
        qr_body['guaranteed'] = {'reserved': True}
        if isinstance(reservation, str):
            # Target a SPECIFIC reservation by name.
            qr_body['reservationName'] = (
                reservation if '/' in reservation else
                f'projects/{project}/locations/{zone}/reservations/'
                f'{reservation}')
    op = gcp_api.create_queued_resource(project, zone, qr_id, qr_body)
    gcp_api.wait_tpu_operation(op)
    deadline = time.time() + _queued_timeout_s()
    interval = 5.0
    missing_polls = 0
    active = False
    try:
        while True:
            qr = gcp_api.get_queued_resource(project, zone, qr_id)
            if qr is None:
                # Created but not visible: tolerate brief
                # read-after-write lag, then fail over rather than burn
                # the whole timeout.
                missing_polls += 1
                if missing_polls >= 3:
                    raise exceptions.ProvisionError(
                        f'Queued resource {qr_id} disappeared after '
                        'creation; failing over.', no_failover=False)
                time.sleep(interval)
                continue
            missing_polls = 0
            state = (qr.get('state') or {}).get('state', 'UNKNOWN')
            if state == 'ACTIVE':
                active = True
                return
            if state in ('FAILED', 'SUSPENDED', 'SUSPENDING'):
                detail = (qr.get('state') or {}).get('stateInitiator',
                                                     '')
                raise exceptions.ProvisionError(
                    f'Queued resource {qr_id} entered {state} {detail};'
                    f' failing over.', no_failover=False)
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'Queued resource {qr_id} still {state} after '
                    f'{_queued_timeout_s():.0f}s; failing over.',
                    no_failover=False)
            time.sleep(interval)
            interval = min(interval * 1.3, 30.0)
    finally:
        if not active:
            # Covers FAILED/timeout AND interruption (Ctrl-C, kill):
            # a pending request left behind would later turn ACTIVE
            # and bill capacity no cluster record tracks.
            try:
                gcp_api.delete_queued_resource(project, zone, qr_id)
            except gcp_api.GcpApiError:
                pass


def _run_tpu_slices(project: str, region: str, zone: str,
                    cluster_name_on_cloud: str,
                    config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    existing = _list_cluster_tpu_nodes(project, zone, cluster_name_on_cloud)
    ready = [n for n in existing
             if n['state'] in ('READY', 'CREATING', 'STARTING')]
    stopped = [n for n in existing if n['state'] == 'STOPPED']
    resumed: List[str] = []
    if config.resume_stopped_nodes:
        for node in stopped:
            node_id = node['name'].rsplit('/', 1)[-1]
            op = gcp_api.start_tpu_node(project, zone, node_id)
            gcp_api.wait_tpu_operation(op)
            resumed.append(node_id)
            ready.append(node)

    to_create = config.count - len(ready)
    created: List[str] = []
    taken = {n['name'].rsplit('/', 1)[-1] for n in existing}
    queued = node_cfg.get('provision_mode') == 'queued'
    if not queued and isinstance(node_cfg.get('reservation'), str):
        logger.warning(
            'A NAMED reservation can only be targeted through queued '
            'provisioning; direct mode requests any reserved capacity. '
            "Set accelerator_args: {provision_mode: queued} to target "
            f'{node_cfg["reservation"]!r}.')
    fresh = _fresh_node_names(cluster_name_on_cloud, taken,
                              max(to_create, 0))
    if queued and fresh:
        # One multi-nodeSpec request: gang admission for the whole
        # cluster's slices, one ACTIVE wait.
        bodies = [_tpu_node_body(node_cfg, cluster_name_on_cloud,
                                 config) for _ in fresh]
        logger.debug(f'Creating {len(fresh)} TPU node(s) via one '
                     f'queuedResource ({node_cfg["tpu_type"]}, zone '
                     f'{zone})')
        _create_via_queued_resource(project, zone, fresh, bodies,
                                    node_cfg)
        created.extend(fresh)
    else:
        for node_id in fresh:
            body = _tpu_node_body(node_cfg, cluster_name_on_cloud,
                                  config)
            logger.debug(f'Creating TPU node {node_id} '
                         f'({node_cfg["tpu_type"]}, zone {zone})')
            op = gcp_api.create_tpu_node(project, zone, node_id, body)
            gcp_api.wait_tpu_operation(op)
            created.append(node_id)

    all_nodes = _list_cluster_tpu_nodes(project, zone, cluster_name_on_cloud)
    names = sorted(n['name'].rsplit('/', 1)[-1] for n in all_nodes
                   if n['state'] not in ('DELETING', 'TERMINATED'))
    if not names:
        raise exceptions.ProvisionError(
            f'No TPU nodes exist for {cluster_name_on_cloud} after '
            'provisioning.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=zone,
        head_instance_id=names[0],
        resumed_instance_ids=resumed,
        created_instance_ids=created,
    )


# GCE acceleratorType ids for attachable GPUs (n1-family attach;
# a2/g2/a3 machine types come with their GPUs bundled and must NOT
# carry guestAccelerators).
_GCE_GPU_TYPES = {
    'A100': 'nvidia-tesla-a100',
    'A100-80GB': 'nvidia-a100-80gb',
    'L4': 'nvidia-l4',
    'H100': 'nvidia-h100-80gb',
    'T4': 'nvidia-tesla-t4',
    'V100': 'nvidia-tesla-v100',
    'P100': 'nvidia-tesla-p100',
}
_BUNDLED_GPU_FAMILIES = ('a2-', 'g2-', 'a3-')


def _gpu_body_parts(node_cfg: Dict[str, Any],
                    zone: str) -> Tuple[List[Dict[str, Any]], bool]:
    """(guestAccelerators, is_gpu_vm) for the instance body.

    GPU VMs must schedule with onHostMaintenance=TERMINATE (GCE cannot
    live-migrate them); bundled-GPU machine families carry no
    guestAccelerators field, attachable GPUs (n1 + T4/V100/...) do.
    Reference behavior: sky/templates/gcp-ray.yml.j2 GPU sections.
    """
    instance_type = node_cfg.get('instance_type', '')
    if instance_type.startswith(_BUNDLED_GPU_FAMILIES):
        # Bundled families ARE GPU VMs even when requested by bare
        # instance_type with no accelerators dict.
        return [], True
    accelerators = node_cfg.get('accelerators') or {}
    if not accelerators:
        return [], False
    guest = []
    for name, count in accelerators.items():
        gce_type = _GCE_GPU_TYPES.get(name)
        if gce_type is None:
            raise exceptions.ProvisionError(
                f'GPU {name!r} has no GCE acceleratorType mapping; '
                f'known: {sorted(_GCE_GPU_TYPES)}. Use a bundled-GPU '
                'machine type (a2/g2/a3) or GKE/AWS.')
        guest.append({
            'acceleratorType':
                f'zones/{zone}/acceleratorTypes/{gce_type}',
            'acceleratorCount': int(count),
        })
    return guest, True


def _run_gce_instances(project: str, region: str, zone: str,
                       cluster_name_on_cloud: str,
                       config: common.ProvisionConfig
                       ) -> common.ProvisionRecord:
    node_cfg = config.node_config
    label_filter = f'labels.{_LABEL_CLUSTER}={cluster_name_on_cloud}'
    existing = gcp_api.list_instances(project, zone, label_filter)
    running = [i for i in existing
               if i['status'] in ('RUNNING', 'PROVISIONING', 'STAGING')]
    stopped = [i for i in existing if i['status'] == 'TERMINATED']
    resumed: List[str] = []
    if config.resume_stopped_nodes:
        for inst in stopped:
            op = gcp_api.instance_action(project, zone, inst['name'],
                                         'start')
            gcp_api.wait_zone_operation(project, zone, op)
            resumed.append(inst['name'])
            running.append(inst)

    to_create = config.count - len(running)
    created: List[str] = []
    machine_type = (f'zones/{zone}/machineTypes/'
                    f'{node_cfg["instance_type"]}')
    guest_accelerators, is_gpu_vm = _gpu_body_parts(node_cfg, zone)
    taken = {i['name'] for i in existing}
    for name in _fresh_node_names(cluster_name_on_cloud, taken,
                                  max(to_create, 0)):
        body: Dict[str, Any] = {
            'name': name,
            'machineType': machine_type,
            'labels': {
                _LABEL_CLUSTER: cluster_name_on_cloud,
                **{k.lower(): str(v).lower()
                   for k, v in config.tags.items()},
            },
            'disks': [{
                'boot': True,
                'autoDelete': True,
                'initializeParams': {
                    'sourceImage': node_cfg.get('image_id'),
                    'diskSizeGb': str(node_cfg.get('disk_size', 256)),
                },
            }],
            'networkInterfaces': [{
                'network': 'global/networks/default',
                'accessConfigs': [{
                    'name': 'External NAT',
                    'type': 'ONE_TO_ONE_NAT',
                }],
            }],
            'metadata': {
                'items': [{
                    'key': 'ssh-keys',
                    'value':
                        config.authentication_config.get('ssh_keys', ''),
                }],
            },
            'scheduling': {
                'preemptible': bool(node_cfg.get('use_spot')),
                'automaticRestart': not node_cfg.get('use_spot'),
            },
        }
        if is_gpu_vm:
            # GCE cannot live-migrate GPU VMs.
            body['scheduling']['onHostMaintenance'] = 'TERMINATE'
            if guest_accelerators:
                body['guestAccelerators'] = guest_accelerators
        op = gcp_api.insert_instance(project, zone, body)
        gcp_api.wait_zone_operation(project, zone, op)
        created.append(name)

    all_insts = gcp_api.list_instances(project, zone, label_filter)
    names = sorted(i['name'] for i in all_insts
                   if i['status'] not in ('STOPPING', 'TERMINATED'))
    if not names:
        raise exceptions.ProvisionError(
            f'No instances exist for {cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=zone,
        head_instance_id=names[0],
        resumed_instance_ids=resumed,
        created_instance_ids=created,
    )


def _list_cluster_tpu_nodes(project: str, zone: str,
                            cluster_name_on_cloud: str
                            ) -> List[Dict[str, Any]]:
    nodes = gcp_api.list_tpu_nodes(project, zone)
    return [n for n in nodes
            if n.get('labels', {}).get(_LABEL_CLUSTER) ==
            cluster_name_on_cloud]


# ---------------------------------------------------------------------------
# stop / terminate / query
# ---------------------------------------------------------------------------
def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    project = _project(provider_config)
    zone = (provider_config or {})['zone']
    if (provider_config or {}).get('tpu_vm'):
        nodes = _list_cluster_tpu_nodes(project, zone, cluster_name_on_cloud)
        for node in nodes:
            if len(node.get('networkEndpoints', [])) > 1:
                raise exceptions.NotSupportedError(
                    'TPU pod slices cannot be stopped — terminate instead '
                    '(reference parity: sky/clouds/gcp.py:193-204).')
            node_id = node['name'].rsplit('/', 1)[-1]
            op = gcp_api.stop_tpu_node(project, zone, node_id)
            gcp_api.wait_tpu_operation(op)
        return
    label_filter = f'labels.{_LABEL_CLUSTER}={cluster_name_on_cloud}'
    insts = gcp_api.list_instances(project, zone, label_filter)
    head = min((i['name'] for i in insts), default=None)
    for inst in insts:
        if worker_only and inst['name'] == head:
            continue
        op = gcp_api.instance_action(project, zone, inst['name'], 'stop')
        gcp_api.wait_zone_operation(project, zone, op)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    project = _project(provider_config)
    zone = (provider_config or {})['zone']
    if (provider_config or {}).get('tpu_vm'):
        nodes = _list_cluster_tpu_nodes(project, zone, cluster_name_on_cloud)
        names = sorted(n['name'].rsplit('/', 1)[-1] for n in nodes)
        head = names[0] if names else None
        queued = (provider_config or {}).get('provision_mode') == 'queued'
        if queued and worker_only:
            # A gang queuedResource covers head+workers together; the
            # TPU API does not allow deleting a subset of its nodes.
            # No in-tree caller uses worker_only; refuse loudly rather
            # than leave the request referencing deleted nodes.
            logger.warning(
                f'{cluster_name_on_cloud}: queued-mode clusters tear '
                'down atomically; ignoring worker_only teardown.')
            return
        ops = []
        covered: set = set()
        if queued:
            # Sweep the cluster's queued requests FIRST: this also
            # reaps pending (no-node-yet) requests that would otherwise
            # turn ACTIVE later and bill untracked capacity.  Their
            # force-delete removes any materialized nodes too.
            # Exact-name match (cluster-qr ids are '{cluster}-{idx}-qr')
            # so a sibling cluster whose name extends ours is untouched.
            qr_pat = re.compile(
                re.escape(cluster_name_on_cloud) + r'-\d+-qr$')
            for qr in gcp_api.list_queued_resources(project, zone):
                qr_name = qr.get('name', '').rsplit('/', 1)[-1]
                if not qr_pat.fullmatch(qr_name):
                    continue
                for spec in ((qr.get('tpu') or {}).get('nodeSpec')
                             or []):
                    if spec.get('nodeId'):
                        covered.add(spec['nodeId'])
                ops.append(gcp_api.delete_queued_resource(
                    project, zone, qr_name))
        for node_id in names:
            if worker_only and node_id == head:
                continue
            if node_id in covered:
                continue  # dies with its queued request
            ops.append(gcp_api.delete_tpu_node(project, zone, node_id))
        for op in ops:
            gcp_api.wait_tpu_operation(op)
        return
    label_filter = f'labels.{_LABEL_CLUSTER}={cluster_name_on_cloud}'
    insts = gcp_api.list_instances(project, zone, label_filter)
    head = min((i['name'] for i in insts), default=None)
    for inst in insts:
        if worker_only and inst['name'] == head:
            continue
        op = gcp_api.delete_instance(project, zone, inst['name'])
        gcp_api.wait_zone_operation(project, zone, op)


_TPU_STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'READY': 'running',
    'RESTARTING': 'pending',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'terminated',
    'TERMINATED': 'terminated',
    'PREEMPTED': 'terminated',
    'REPAIRING': 'pending',
}
_GCE_STATE_MAP = {
    'PROVISIONING': 'pending',
    'STAGING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'SUSPENDED': 'stopped',
    'TERMINATED': 'stopped',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    project = _project(provider_config)
    zone = (provider_config or {})['zone']
    out: Dict[str, Optional[str]] = {}
    if (provider_config or {}).get('tpu_vm'):
        for node in _list_cluster_tpu_nodes(project, zone,
                                            cluster_name_on_cloud):
            status = _TPU_STATE_MAP.get(node['state'])
            if non_terminated_only and status == 'terminated':
                continue
            out[node['name'].rsplit('/', 1)[-1]] = status
        return out
    label_filter = f'labels.{_LABEL_CLUSTER}={cluster_name_on_cloud}'
    for inst in gcp_api.list_instances(project, zone, label_filter):
        status = _GCE_STATE_MAP.get(inst['status'])
        if non_terminated_only and status == 'terminated':
            continue
        out[inst['name']] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 1200) -> None:
    del region
    deadline = time.time() + timeout_s
    target = state or 'running'
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud, provider_config)
        if statuses and all(s == target for s in statuses.values()):
            return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'Instances of {cluster_name_on_cloud} did not reach {target} within '
        f'{timeout_s}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region
    project = _project(provider_config)
    zone = (provider_config or {})['zone']
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id: Optional[str] = None
    if (provider_config or {}).get('tpu_vm'):
        nodes = _list_cluster_tpu_nodes(project, zone, cluster_name_on_cloud)
        for node in sorted(nodes, key=lambda n: n['name']):
            if node['state'] != 'READY':
                continue
            node_id = node['name'].rsplit('/', 1)[-1]
            endpoints = node.get('networkEndpoints', [])
            internal = [ep.get('ipAddress') for ep in endpoints]
            external = [
                ep.get('accessConfig', {}).get('externalIp')
                for ep in endpoints
            ]
            if not internal:
                continue
            instances[node_id] = [
                common.InstanceInfo(
                    instance_id=node_id,
                    internal_ip=internal[0],
                    external_ip=external[0] if external else None,
                    tags=node.get('labels', {}),
                    host_ips=internal,
                    host_external_ips=external,
                )
            ]
            if head_id is None:
                head_id = node_id
    else:
        label_filter = f'labels.{_LABEL_CLUSTER}={cluster_name_on_cloud}'
        for inst in sorted(gcp_api.list_instances(project, zone,
                                                  label_filter),
                           key=lambda i: i['name']):
            if inst['status'] != 'RUNNING':
                continue
            nic = inst.get('networkInterfaces', [{}])[0]
            access = nic.get('accessConfigs', [{}])
            instances[inst['name']] = [
                common.InstanceInfo(
                    instance_id=inst['name'],
                    internal_ip=nic.get('networkIP'),
                    external_ip=access[0].get('natIP') if access else None,
                    tags=inst.get('labels', {}),
                )
            ]
            if head_id is None:
                head_id = inst['name']
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name=_PROVIDER,
        provider_config=provider_config,
        ssh_user=(provider_config or {}).get('ssh_user', 'skytpu'),
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    project = _project(provider_config)
    rule_name = f'{cluster_name_on_cloud}-ports'
    allowed = [{
        'IPProtocol': 'tcp',
        'ports': [p.replace('-', '-') for p in ports],
    }]
    body = {
        'name': rule_name,
        'network': 'global/networks/default',
        'direction': 'INGRESS',
        'sourceRanges': ['0.0.0.0/0'],
        'allowed': allowed,
        'targetTags': [cluster_name_on_cloud],
    }
    try:
        gcp_api.insert_firewall_rule(project, body)
    except gcp_api.GcpApiError as e:
        if e.status_code != 409:  # already exists
            raise _classify_api_error(e) from e


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del ports
    project = _project(provider_config)
    try:
        gcp_api.delete_firewall_rule(project,
                                     f'{cluster_name_on_cloud}-ports')
    except gcp_api.GcpApiError as e:
        if e.status_code != 404:
            raise _classify_api_error(e) from e
