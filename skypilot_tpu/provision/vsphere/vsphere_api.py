"""Minimal vCenter Automation (REST) API client (JSON over urllib).

Counterpart of the reference's sky/provision/vsphere/* (pyvmomi SOAP
+ vSphere Automation SDK); SDK-free against the vCenter REST API:
POST /api/session (basic auth) -> session token header
`vmware-api-session-id`, then /api/vcenter/vm endpoints.

Credentials from env VSPHERE_HOST / VSPHERE_USER / VSPHERE_PASSWORD
or ~/.vsphere/credential.yaml (the reference path).  All calls route
through `request`, the single test seam.
"""
from __future__ import annotations

import base64
import dataclasses
import json
import os
import re
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

_TIMEOUT = 60.0
_CREDENTIALS_FILE = '~/.vsphere/credential.yaml'

_session: Dict[str, str] = {}


class VsphereApiError(exceptions.ProvisionError):

    def __init__(self, status_code: int, code: str, message: str) -> None:
        no_failover = status_code in (401, 403)
        super().__init__(
            f'vSphere API error {status_code} {code}: {message}',
            no_failover=no_failover)
        self.status_code = status_code
        self.code = code


@dataclasses.dataclass(frozen=True)
class VsphereCredentials:
    host: str
    user: str
    password: str


def load_credentials() -> Optional[VsphereCredentials]:
    env = {k: os.environ.get(f'VSPHERE_{k.upper()}')
           for k in ('host', 'user', 'password')}
    if all(env.values()):
        return VsphereCredentials(**env)  # type: ignore[arg-type]
    path = os.path.expanduser(
        os.environ.get('VSPHERE_CREDENTIALS_FILE', _CREDENTIALS_FILE))
    if not os.path.exists(path):
        return None
    values: Dict[str, str] = {}
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                m = re.match(r'\s*(host|user|password)\s*:\s*(\S+)',
                             line.rstrip())
                if m:
                    values[m.group(1)] = m.group(2).strip('\'"')
    except OSError:
        return None
    if {'host', 'user', 'password'} <= set(values):
        return VsphereCredentials(values['host'], values['user'],
                                  values['password'])
    return None


def _urlopen(req: urllib.request.Request):
    # On-prem vCenters overwhelmingly run self-signed certs.
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return urllib.request.urlopen(req, timeout=_TIMEOUT, context=ctx)


def _login() -> str:
    creds = load_credentials()
    if creds is None:
        raise VsphereApiError(401, 'NoCredentials',
                              'no vSphere credentials')
    token = _session.get('token')
    if token:
        return token
    basic = base64.b64encode(
        f'{creds.user}:{creds.password}'.encode()).decode()
    req = urllib.request.Request(
        f'https://{creds.host}/api/session', method='POST',
        headers={'Authorization': f'Basic {basic}'})
    try:
        with _urlopen(req) as resp:
            token = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raise VsphereApiError(e.code, 'SessionCreate',
                              e.read().decode(errors='replace')[:200]) \
            from None
    except urllib.error.URLError as e:
        raise VsphereApiError(0, 'Unreachable', str(e)) from None
    _session['token'] = token
    return token


def request(method: str, path: str,
            body: Optional[Dict[str, Any]] = None) -> Any:
    creds = load_credentials()
    if creds is None:
        raise VsphereApiError(401, 'NoCredentials',
                              'no vSphere credentials')
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f'https://{creds.host}{path}', data=data, method=method,
        headers={'vmware-api-session-id': _login(),
                 'Content-Type': 'application/json'})
    try:
        with _urlopen(req) as resp:
            text = resp.read()
            return json.loads(text) if text.strip() else {}
    except urllib.error.HTTPError as e:
        if e.code == 401:
            _session.pop('token', None)  # session expired; re-login
        text = e.read().decode(errors='replace')
        code = 'unknown'
        if 'resource' in text.lower() or 'insufficient' in \
                text.lower():
            code = 'insufficient-capacity'
        raise VsphereApiError(e.code, code, text[:200]) from None
    except urllib.error.URLError as e:
        raise VsphereApiError(0, 'Unreachable', str(e)) from None


def list_vms(name_prefix: str) -> List[Dict[str, Any]]:
    vms = request('GET', '/api/vcenter/vm') or []
    return [vm for vm in vms
            if str(vm.get('name', '')).startswith(name_prefix)]


def clone_vm(source_vm: str, name: str) -> str:
    """Full clone of the template VM; returns the new VM id."""
    return str(request('POST', '/api/vcenter/vm?action=clone', {
        'source': source_vm,
        'name': name,
        'power_on': True,
    }))


def power_action(vm_id: str, action: str) -> None:
    """start | stop."""
    request('POST', f'/api/vcenter/vm/{vm_id}/power?action={action}')


def delete_vm(vm_id: str) -> None:
    try:
        request('DELETE', f'/api/vcenter/vm/{vm_id}')
    except VsphereApiError as e:
        if e.status_code != 404:
            raise


def guest_ip(vm_id: str) -> Optional[str]:
    """The guest-tools-reported primary IP (None until tools are up)."""
    try:
        info = request('GET',
                       f'/api/vcenter/vm/{vm_id}/guest/networking')
    except VsphereApiError:
        return None
    for itf in (info or {}).get('interfaces', []):
        ip = (itf.get('ip') or {}).get('ip_addresses', [])
        for addr in ip:
            if addr.get('state') == 'PREFERRED':
                return str(addr.get('ip_address'))
    return None
