"""vSphere provisioner: the uniform provision interface.

Counterpart of the reference's sky/provision/vsphere/instance.py
(pyvmomi).  VMs clone from a configured content-library/template VM
(`vsphere.template_vm` config), are named `<cluster>-<idx>`, support
power stop/start, and report IPs via guest tools.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.vsphere import vsphere_api

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'vsphere'


def _classify(e: vsphere_api.VsphereApiError) -> Exception:
    if e.code == 'insufficient-capacity':
        return exceptions.ResourcesUnavailableError(str(e))
    return e


def _template_vm() -> str:
    from skypilot_tpu import config as config_lib
    template = config_lib.get_nested(('vsphere', 'template_vm'), None)
    if not template:
        raise exceptions.ProvisionError(
            'vSphere provisioning needs config vsphere.template_vm '
            '(the VM/template to clone; it must have the framework '
            'SSH key in authorized_keys).')
    return template


def _cluster_vms(cluster_name_on_cloud: str) -> List[Dict[str, Any]]:
    pattern = re.compile(
        rf'^{re.escape(cluster_name_on_cloud)}-\d{{4}}$')
    return sorted(
        (vm for vm in vsphere_api.list_vms(
            f'{cluster_name_on_cloud}-')
         if pattern.fullmatch(str(vm.get('name', '')))),
        key=lambda vm: str(vm.get('name')))


def _power(vm: Dict[str, Any]) -> str:
    return str(vm.get('power_state', 'UNKNOWN')).upper()


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region  # on-prem: the vCenter host IS the site
    try:
        template = _template_vm()
        existing = _cluster_vms(cluster_name_on_cloud)
        running = [vm for vm in existing
                   if _power(vm) == 'POWERED_ON']
        stopped = [vm for vm in existing
                   if _power(vm) == 'POWERED_OFF']

        resumed: List[str] = []
        if config.resume_stopped_nodes and stopped:
            need = config.count - len(running)
            for vm in stopped[:max(need, 0)]:
                vsphere_api.power_action(str(vm['vm']), 'start')
                resumed.append(str(vm['vm']))
            running += [vm for vm in stopped
                        if str(vm['vm']) in resumed]

        created: List[str] = []
        to_create = config.count - len(running)
        if to_create > 0:
            base = len(existing)
            for i in range(to_create):
                created.append(vsphere_api.clone_vm(
                    template,
                    f'{cluster_name_on_cloud}-{base + i:04d}'))
    except vsphere_api.VsphereApiError as e:
        raise _classify(e) from None
    ids = sorted([str(vm['vm']) for vm in running] + created)
    if not ids:
        raise exceptions.ResourcesUnavailableError(
            f'vSphere returned no VMs for {cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER, cluster_name=cluster_name_on_cloud,
        region='vsphere', zone=None, head_instance_id=ids[0],
        resumed_instance_ids=resumed, created_instance_ids=created)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    vms = [vm for vm in _cluster_vms(cluster_name_on_cloud)
           if _power(vm) == 'POWERED_ON']
    ids = sorted(str(vm['vm']) for vm in vms)
    if worker_only and ids:
        ids = ids[1:]
    for vid in ids:
        vsphere_api.power_action(vid, 'stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    vms = _cluster_vms(cluster_name_on_cloud)
    ids = sorted(str(vm['vm']) for vm in vms)
    if worker_only and ids:
        ids = ids[1:]
    for vid in ids:
        # Powered-on VMs cannot be deleted: stop first, tolerant of
        # already-off.
        try:
            vsphere_api.power_action(vid, 'stop')
        except vsphere_api.VsphereApiError:
            pass
        vsphere_api.delete_vm(vid)


_STATUS_MAP = {
    'POWERED_ON': 'running',
    'POWERED_OFF': 'stopped',
    'SUSPENDED': 'stopped',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    del non_terminated_only  # deleted VMs vanish from inventory
    out: Dict[str, Optional[str]] = {}
    for vm in _cluster_vms(cluster_name_on_cloud):
        out[str(vm['vm'])] = _STATUS_MAP.get(_power(vm))
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: str = 'running', timeout: float = 900.0) -> None:
    """POWERED_ON is not enough to SSH: wait for guest-tools IPs too."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        vms = _cluster_vms(cluster_name_on_cloud)
        if vms:
            if state != 'running':
                if all(_STATUS_MAP.get(_power(vm)) == state
                       for vm in vms):
                    return
            elif all(_power(vm) == 'POWERED_ON'
                     and vsphere_api.guest_ip(str(vm['vm']))
                     for vm in vms):
                return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'{cluster_name_on_cloud}: VMs did not reach {state!r} '
        f'(with guest IPs) within {timeout}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for vm in _cluster_vms(cluster_name_on_cloud):
        if _power(vm) != 'POWERED_ON':
            continue
        vid = str(vm['vm'])
        ip = vsphere_api.guest_ip(vid)
        if not ip:
            continue
        instances[vid] = [common.InstanceInfo(
            instance_id=vid,
            internal_ip=ip,
            external_ip=ip,  # on-prem: one routable address
            tags={'name': str(vm.get('name'))},
        )]
    head = sorted(instances)[0] if instances else None
    return common.ClusterInfo(
        instances=instances, head_instance_id=head,
        provider_name=_PROVIDER, provider_config=provider_config,
        ssh_user='ubuntu')


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.info('vSphere networking is site-managed; ports %s are '
                'assumed reachable on-prem.', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('vSphere networking is site-managed; nothing to close for %s.', ports)
