"""FluidStack provisioner: the uniform provision interface.

Counterpart of the reference's sky/provision/fluidstack/instance.py.
FluidStack semantics: instances launch one at a time by (gpu_type,
gpu_count, region) — the instance_type grammar is
`<GPU_TYPE>::<count>` as in the reference's catalog — carry a NAME
(our cluster tag), no stop support (the API has a /stop endpoint but
billing continues; the reference declares STOP unsupported and so do
we), platform-registered SSH keys.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.fluidstack import fluidstack_api

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'fluidstack'
_KEY_NAME = 'skytpu-key'


def parse_instance_type(instance_type: str):
    """'H100_PCIE_80GB::2' -> ('H100_PCIE_80GB', 2)."""
    gpu_type, sep, count = instance_type.partition('::')
    if not sep:
        raise exceptions.ProvisionError(
            f'bad FluidStack instance type {instance_type!r} '
            f'(want <GPU_TYPE>::<count>)')
    return gpu_type, int(count)


def _classify(e: fluidstack_api.FluidstackApiError) -> Exception:
    if e.code == 'out-of-stock':
        return exceptions.ResourcesUnavailableError(str(e))
    return e


def _cluster_instances(cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    return sorted(
        (i for i in fluidstack_api.list_instances()
         if i.get('name') == cluster_name_on_cloud),
        key=lambda i: str(i.get('id')))


def _ensure_ssh_key(auth_config: Dict[str, Any]) -> str:
    ssh_keys = (auth_config or {}).get('ssh_keys', '')
    if ':' not in ssh_keys:
        keys = fluidstack_api.list_ssh_keys()
        if not keys:
            raise exceptions.ProvisionError(
                'FluidStack requires an SSH key: none in the launch '
                'auth config and none registered with the account.')
        return str(keys[0]['name'])
    pub = ssh_keys.split(':', 1)[1]
    for key in fluidstack_api.list_ssh_keys():
        if str(key.get('public_key', '')).strip() == pub.strip():
            return str(key['name'])
    name = f'{_KEY_NAME}-{abs(hash(pub)) % 10**8}'
    fluidstack_api.add_ssh_key(name, pub)
    return name


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    try:
        existing = _cluster_instances(cluster_name_on_cloud)
        live = [i for i in existing
                if str(i.get('status')) in
                ('running', 'pending', 'provisioning')]
        to_create = config.count - len(live)
        created: List[str] = []
        if to_create > 0:
            gpu_type, gpu_count = parse_instance_type(
                node_cfg['instance_type'])
            key_name = _ensure_ssh_key(config.authentication_config)
            for _ in range(to_create):
                created.append(fluidstack_api.create_instance(
                    gpu_type, gpu_count, region,
                    cluster_name_on_cloud, key_name))
    except fluidstack_api.FluidstackApiError as e:
        raise _classify(e) from None
    ids = sorted([str(i['id']) for i in live] + created)
    if not ids:
        raise exceptions.ResourcesUnavailableError(
            f'FluidStack returned no instances for '
            f'{cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=None,
        head_instance_id=ids[0],
        resumed_instance_ids=[],
        created_instance_ids=created,
    )


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    raise exceptions.NotSupportedError(
        'FluidStack instances cannot be stopped; use `sky down` '
        '(terminate).')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    ids = sorted(
        str(i['id'])
        for i in _cluster_instances(cluster_name_on_cloud)
        if str(i.get('status')) not in ('terminated', 'terminating'))
    if worker_only and ids:
        ids = ids[1:]
    for iid in ids:
        fluidstack_api.delete_instance(iid)


_STATUS_MAP = {
    'provisioning': 'pending',
    'pending': 'pending',
    'running': 'running',
    'stopped': 'stopped',
    'terminating': 'terminated',
    'terminated': 'terminated',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(cluster_name_on_cloud):
        status = _STATUS_MAP.get(str(inst.get('status')))
        if non_terminated_only and status == 'terminated':
            continue
        out[str(inst['id'])] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: str = 'running', timeout: float = 900.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud, None,
                                   non_terminated_only=False)
        live = [s for s in statuses.values() if s != 'terminated']
        if live and all(s == state for s in live):
            return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'{cluster_name_on_cloud}: instances did not reach '
        f'{state!r} within {timeout}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for inst in _cluster_instances(cluster_name_on_cloud):
        if str(inst.get('status')) != 'running':
            continue
        iid = str(inst['id'])
        instances[iid] = [common.InstanceInfo(
            instance_id=iid,
            internal_ip=str(inst.get('private_ip') or ''),
            external_ip=inst.get('ip_address') or inst.get('ip'),
            tags={'name': str(inst.get('name'))},
        )]
    head = sorted(instances)[0] if instances else None
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head,
        provider_name=_PROVIDER,
        provider_config=provider_config,
        ssh_user='ubuntu',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.warning('FluidStack has no per-cluster firewall API; '
                   'ensure %s are reachable.', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('FluidStack has no per-cluster firewall API; nothing to close for %s.', ports)
