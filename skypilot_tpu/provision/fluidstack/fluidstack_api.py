"""Minimal FluidStack REST client (JSON over urllib).

Counterpart of the reference's
sky/provision/fluidstack/fluidstack_utils.py (requests-based).
API: https://platform.fluidstack.io/ with an `api-key` header; key
from env FLUIDSTACK_API_KEY, then ~/.fluidstack/api_key (the
reference's path).  All calls route through `request`, the single
test seam.
"""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ROOT = 'https://platform.fluidstack.io'
_TIMEOUT = 60.0
_KEY_FILE = '~/.fluidstack/api_key'


class FluidstackApiError(exceptions.ProvisionError):

    def __init__(self, status_code: int, code: str, message: str) -> None:
        no_failover = status_code in (401, 403)
        super().__init__(
            f'FluidStack API error {status_code} {code}: {message}',
            no_failover=no_failover)
        self.status_code = status_code
        self.code = code


def load_api_key() -> Optional[str]:
    key = os.environ.get('FLUIDSTACK_API_KEY')
    if key:
        return key
    path = os.path.expanduser(
        os.environ.get('FLUIDSTACK_KEY_FILE', _KEY_FILE))
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            content = f.read().strip()
        return content or None
    except OSError:
        return None


def request(method: str, path: str,
            body: Optional[Dict[str, Any]] = None) -> Any:
    key = load_api_key()
    if key is None:
        raise FluidstackApiError(401, 'NoCredentials',
                                 'no FluidStack API key found')
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f'{API_ROOT}{path}', data=data, method=method,
        headers={'api-key': key, 'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=_TIMEOUT) as resp:
            text = resp.read()
            return json.loads(text) if text.strip() else {}
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors='replace')
        try:
            err = json.loads(text)
            msg = str(err.get('message', err.get('error', text[:200])))
        except json.JSONDecodeError:
            msg = text[:200]
        code = 'out-of-stock' if 'stock' in msg.lower() else 'unknown'
        raise FluidstackApiError(e.code, code, msg) from None
    except urllib.error.URLError as e:
        raise FluidstackApiError(0, 'Unreachable', str(e)) from None


def list_instances() -> List[Dict[str, Any]]:
    return list(request('GET', '/instances') or [])


def create_instance(gpu_type: str, gpu_count: int, region: str,
                    name: str, ssh_key_name: str) -> str:
    resp = request('POST', '/instances', body={
        'gpu_type': gpu_type,
        'gpu_count': gpu_count,
        'region': region,
        'operating_system_label': 'ubuntu_22_04_lts_nvidia',
        'name': name,
        'ssh_key': ssh_key_name,
    })
    instance_id = (resp or {}).get('id')
    if not instance_id:
        raise FluidstackApiError(200, 'out-of-stock',
                                 f'no instance created for {name}')
    return str(instance_id)


def delete_instance(instance_id: str) -> None:
    try:
        request('DELETE', f'/instances/{instance_id}')
    except FluidstackApiError as e:
        if e.status_code != 404:
            raise


def list_ssh_keys() -> List[Dict[str, Any]]:
    return list(request('GET', '/ssh_keys') or [])


def add_ssh_key(name: str, public_key: str) -> None:
    request('POST', '/ssh_keys',
            body={'name': name, 'public_key': public_key})
