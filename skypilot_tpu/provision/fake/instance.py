"""Fake in-process provisioner.

Implements the full provision/api.py interface against
`clouds.fake.FakeCloudState`, making everything past the reference's
`bulk_provision` cloud-API boundary testable hermetically — the tier the
reference lacks (SURVEY.md §4 "no fake-cloud simulator").  TPU slices are
modeled faithfully: one instance record carries per-host IPs, created and
destroyed atomically, preemptible via `state.preempt_cluster()`.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.clouds import fake as fake_cloud
from skypilot_tpu.provision import common

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'fake'


def _state() -> fake_cloud.FakeCloudState:
    return fake_cloud.fake_cloud_state()


def _cluster_instances(cluster_name_on_cloud: str,
                       include_terminated: bool = False
                       ) -> Dict[str, Dict[str, Any]]:
    """Cluster records from the CURRENT transaction snapshot (callers
    mutate the returned records, so they must hold a transaction)."""
    return {
        iid: rec for iid, rec in _state().instances.items()
        if rec['cluster'] == cluster_name_on_cloud and
        (include_terminated or rec['status'] != 'terminated')
    }


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    zone = node_cfg.get('zone') or f'{region}-1'
    num_hosts = int(node_cfg.get('num_tpu_hosts', 1) or 1)
    is_tpu = bool(node_cfg.get('tpu_vm'))

    with _state().transaction() as state:
        existing = _cluster_instances(cluster_name_on_cloud)
        resumed: List[str] = []
        if config.resume_stopped_nodes:
            for iid, rec in existing.items():
                if rec['status'] == 'stopped':
                    rec['status'] = 'running'
                    resumed.append(iid)
        running = [iid for iid, rec in existing.items()
                   if rec['status'] == 'running']
        to_create = config.count - len(running)
        # Capacity/fault check counts hosts: a whole slice takes
        # num_hosts slots and is admitted or rejected atomically (slice
        # gang admission).
        if to_create > 0:
            state.check_and_take_capacity(zone, to_create * num_hosts)
        delay = state.provision_delay_s

    # Simulated provisioning latency runs with the control-plane lock
    # RELEASED, so tests/controllers can race fault injections against
    # an in-flight provision (capacity is already reserved above).
    if to_create > 0 and delay:
        time.sleep(delay)

    created: List[str] = []
    with _state().transaction() as state:
        for _ in range(to_create):
            iid = state.next_id()
            seq = len(state.instances)
            host_ips = [f'10.0.{seq}.{h + 1}' for h in range(num_hosts)]
            state.instances[iid] = {
                'id': iid,
                'cluster': cluster_name_on_cloud,
                'region': region,
                'zone': zone,
                'status': 'running',
                'preempted': False,
                'spot': bool(node_cfg.get('use_spot')),
                'tpu': is_tpu,
                'host_ips': host_ips,
                'created_at': time.time(),
                'tags': dict(config.tags),
            }
            created.append(iid)

        all_insts = sorted(_cluster_instances(cluster_name_on_cloud))
        head_id = all_insts[0]
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=zone,
        head_instance_id=head_id,
        resumed_instance_ids=resumed,
        created_instance_ids=created,
    )


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    with _state().transaction():
        insts = _cluster_instances(cluster_name_on_cloud)
        head = sorted(insts)[0] if insts else None
        for iid, rec in insts.items():
            if worker_only and iid == head:
                continue
            if rec['tpu'] and len(rec['host_ips']) > 1:
                from skypilot_tpu import exceptions
                raise exceptions.NotSupportedError(
                    'TPU pod slices cannot be stopped.')
            rec['status'] = 'stopped'


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    with _state().transaction():
        insts = _cluster_instances(cluster_name_on_cloud)
        head = sorted(insts)[0] if insts else None
        for iid, rec in insts.items():
            if worker_only and iid == head:
                continue
            rec['status'] = 'terminated'


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for iid, rec in _cluster_instances(cluster_name_on_cloud,
                                       include_terminated=True).items():
        status = rec['status']
        if non_terminated_only and status == 'terminated':
            continue
        out[iid] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # instant in the fake cloud


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    insts = _cluster_instances(cluster_name_on_cloud)
    for iid, rec in insts.items():
        if rec['status'] != 'running':
            continue
        instances[iid] = [
            common.InstanceInfo(
                instance_id=iid,
                internal_ip=rec['host_ips'][0],
                external_ip=None,
                tags=rec['tags'],
                host_ips=list(rec['host_ips']),
            )
        ]
    head_id = sorted(insts)[0] if insts else None
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name=_PROVIDER,
        provider_config=provider_config,
        ssh_user='fake',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """Recorded on the instance records, so hermetic tests can assert
    the launch path really opened what the resources declared."""
    del provider_config
    with _state().transaction() as state:
        for rec in state.instances.values():
            if rec.get('cluster') == cluster_name_on_cloud:
                opened = rec.setdefault('open_ports', [])
                rec['open_ports'] = sorted(set(opened) | set(ports))


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del provider_config
    with _state().transaction() as state:
        for rec in state.instances.values():
            if rec.get('cluster') == cluster_name_on_cloud:
                rec['open_ports'] = sorted(
                    set(rec.get('open_ports', [])) - set(ports))
