"""Minimal Lambda Cloud REST client (JSON over urllib).

Counterpart of the reference's sky/provision/lambda_cloud/ (which
wraps the same public API): https://cloud.lambdalabs.com/api/v1/ with
Bearer API-key auth.  Key sources: env LAMBDA_API_KEY, then
`~/.lambda_cloud/lambda_keys` ('api_key = <key>' — the reference's
file).  All calls route through `_call`, the single test seam.
"""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ROOT = 'https://cloud.lambdalabs.com/api/v1'
_TIMEOUT = 60.0
_KEY_FILE = '~/.lambda_cloud/lambda_keys'


class LambdaApiError(exceptions.ProvisionError):

    def __init__(self, status_code: int, code: str, message: str) -> None:
        no_failover = status_code in (401, 403)
        super().__init__(
            f'Lambda API error {status_code} {code}: {message}',
            no_failover=no_failover)
        self.status_code = status_code
        self.code = code


def load_api_key() -> Optional[str]:
    key = os.environ.get('LAMBDA_API_KEY')
    if key:
        return key
    path = os.path.expanduser(
        os.environ.get('LAMBDA_KEY_FILE', _KEY_FILE))
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                key_part, sep, value = line.strip().partition('=')
                if sep and key_part.strip() == 'api_key' and \
                        value.strip():
                    return value.strip()
    except OSError:
        return None
    return None


def _call(method: str, path: str,
          body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    key = load_api_key()
    if key is None:
        raise LambdaApiError(401, 'NoCredentials',
                             'no Lambda API key found')
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f'{API_ROOT}{path}', data=data, method=method,
        headers={'Authorization': f'Bearer {key}',
                 'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=_TIMEOUT) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors='replace')
        try:
            err = json.loads(text).get('error', {})
            raise LambdaApiError(e.code, err.get('code', 'unknown'),
                                 err.get('message', text[:200])) \
                from None
        except (json.JSONDecodeError, AttributeError):
            raise LambdaApiError(e.code, 'unknown', text[:200]) \
                from None
    except urllib.error.URLError as e:
        raise LambdaApiError(0, 'Unreachable', str(e)) from None


def list_instances() -> List[Dict[str, Any]]:
    return list(_call('GET', '/instances').get('data', []))


def launch(region: str, instance_type: str, ssh_key_names: List[str],
           quantity: int = 1,
           name: Optional[str] = None) -> List[str]:
    body: Dict[str, Any] = {
        'region_name': region,
        'instance_type_name': instance_type,
        'ssh_key_names': ssh_key_names,
        'quantity': quantity,
    }
    if name:
        body['name'] = name
    out = _call('POST', '/instance-operations/launch', body)
    return list(out.get('data', {}).get('instance_ids', []))


def terminate(instance_ids: List[str]) -> None:
    if instance_ids:
        _call('POST', '/instance-operations/terminate',
              {'instance_ids': instance_ids})


def list_ssh_keys() -> List[Dict[str, Any]]:
    return list(_call('GET', '/ssh-keys').get('data', []))


def add_ssh_key(name: str, public_key: str) -> None:
    _call('POST', '/ssh-keys',
          {'name': name, 'public_key': public_key})
