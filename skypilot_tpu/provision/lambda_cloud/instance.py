"""Lambda Cloud provisioner: the uniform provision interface.

Counterpart of the reference's sky/provision/lambda_cloud/instance.py.
Lambda semantics: instances launch by (region, type, quantity), carry
a NAME (our cluster tag), cannot stop/resume (terminate only — the
cloud declares STOP unsupported), and the platform injects registered
SSH keys, so the framework key is registered via the /ssh-keys API
before launch.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.lambda_cloud import lambda_api

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'lambda'
_KEY_NAME = 'skytpu-key'

_CAPACITY_CODES = {'instance-operations/launch/insufficient-capacity',
                   'insufficient-capacity',
                   'global/quota-exceeded'}


def _classify(e: lambda_api.LambdaApiError) -> Exception:
    if e.code in _CAPACITY_CODES or 'capacity' in e.code:
        return exceptions.ResourcesUnavailableError(str(e))
    return e


def _cluster_instances(cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    return sorted(
        (i for i in lambda_api.list_instances()
         if i.get('name') == cluster_name_on_cloud),
        key=lambda i: str(i.get('id')))


def _ensure_ssh_key(auth_config: Dict[str, Any]) -> List[str]:
    ssh_keys = (auth_config or {}).get('ssh_keys', '')
    if ':' not in ssh_keys:
        # No framework key: fall back to the account's existing keys.
        names = [k['name'] for k in lambda_api.list_ssh_keys()]
        if not names:
            raise exceptions.ProvisionError(
                'Lambda requires an SSH key: none in the launch auth '
                'config and none registered with the account.')
        return names[:1]
    pub = ssh_keys.split(':', 1)[1]
    for key in lambda_api.list_ssh_keys():
        if key.get('public_key', '').strip() == pub.strip():
            return [key['name']]
    name = f'{_KEY_NAME}-{abs(hash(pub)) % 10**8}'
    lambda_api.add_ssh_key(name, pub)
    return [name]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    try:
        existing = _cluster_instances(cluster_name_on_cloud)
        active = [i for i in existing
                  if i.get('status') in ('active', 'booting')]
        to_create = config.count - len(active)
        created: List[str] = []
        if to_create > 0:
            key_names = _ensure_ssh_key(config.authentication_config)
            created = lambda_api.launch(
                region, node_cfg['instance_type'], key_names,
                quantity=to_create, name=cluster_name_on_cloud)
    except lambda_api.LambdaApiError as e:
        raise _classify(e) from None
    ids = sorted([str(i['id']) for i in active] + created)
    if not ids:
        raise exceptions.ResourcesUnavailableError(
            f'Lambda returned no instances for '
            f'{cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=None,
        head_instance_id=ids[0],
        resumed_instance_ids=[],
        created_instance_ids=created,
    )


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    raise exceptions.NotSupportedError(
        'Lambda Cloud cannot stop instances; use `sky down` '
        '(terminate).')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    # Lambda keeps terminated instances in /instances listings for a
    # while — filter them out BEFORE electing the head, or a stale
    # dead instance shadows the real head and worker_only kills it.
    ids = sorted(
        str(i['id'])
        for i in _cluster_instances(cluster_name_on_cloud)
        if i.get('status') not in ('terminated', 'terminating'))
    if worker_only and ids:
        ids = ids[1:]
    lambda_api.terminate(ids)


_STATUS_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'running',
    'terminating': 'terminated',
    'terminated': 'terminated',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(cluster_name_on_cloud):
        status = _STATUS_MAP.get(str(inst.get('status')))
        if non_terminated_only and status == 'terminated':
            continue
        out[str(inst['id'])] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: str = 'running', timeout: float = 900.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud, None,
                                   non_terminated_only=False)
        live = [s for s in statuses.values() if s != 'terminated']
        if live and all(s == state for s in live):
            return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'{cluster_name_on_cloud}: instances did not reach '
        f'{state!r} within {timeout}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for inst in _cluster_instances(cluster_name_on_cloud):
        if inst.get('status') != 'active':
            continue
        iid = str(inst['id'])
        instances[iid] = [common.InstanceInfo(
            instance_id=iid,
            internal_ip=str(inst.get('private_ip') or ''),
            external_ip=inst.get('ip'),
            tags={'name': str(inst.get('name'))},
        )]
    head = sorted(instances)[0] if instances else None
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head,
        provider_name=_PROVIDER,
        provider_config=provider_config,
        ssh_user='ubuntu',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    # Lambda exposes instances on a public IP with open firewalling
    # managed account-wide in their console; nothing per-cluster.
    logger.warning('Lambda open_ports is account-wide (console); '
                   'ensure %s are reachable.', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('Lambda Cloud has no firewall API per cluster; nothing to close for %s.', ports)
