"""OCI provisioner: the uniform provision interface over the oci CLI.

Counterpart of the reference's sky/provision/oci/instance.py (oci
SDK).  Instances are freeform-tagged `skytpu-cluster=<name>`, support
stop/start, and preemptible capacity maps to use_spot.  Flex shapes
(`VM.Standard.E4.Flex-<ocpus>-<mem>` in the catalog grammar)
decompose into --shape-config.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.oci import oci_cli

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'oci'
_FLEX_RE = re.compile(r'^(?P<shape>.+\.Flex)-(?P<ocpus>\d+)-'
                      r'(?P<mem>\d+)$')

_CAPACITY_MARKERS = ('OutOfCapacity', 'LimitExceeded', 'QuotaExceeded',
                     'TooManyRequests')


def parse_shape(instance_type: str):
    """'VM.Standard.E4.Flex-8-32' -> ('VM.Standard.E4.Flex',
    {'ocpus': 4.0, 'memoryInGBs': 32.0}); fixed shapes pass through.
    (OCI Flex ocpus are physical cores: vcpus/2.)"""
    m = _FLEX_RE.match(instance_type)
    if not m:
        return instance_type, None
    return m.group('shape'), {
        'ocpus': int(m.group('ocpus')) / 2.0,
        'memoryInGBs': float(m.group('mem')),
    }


def _classify(e: oci_cli.OciCliError) -> Exception:
    if any(marker in str(e) for marker in _CAPACITY_MARKERS):
        return exceptions.ResourcesUnavailableError(str(e))
    return e


def _net_settings() -> Dict[str, str]:
    from skypilot_tpu import config as config_lib
    settings = {}
    for key in ('subnet_id', 'image_id', 'availability_domain'):
        value = config_lib.get_nested(('oci', key), None)
        if not value:
            raise exceptions.ProvisionError(
                f'OCI provisioning needs config oci.{key}.')
        settings[key] = value
    return settings


def _public_key(auth_config: Dict[str, Any]) -> str:
    ssh_keys = (auth_config or {}).get('ssh_keys', '')
    if ':' not in ssh_keys:
        raise exceptions.ProvisionError(
            'OCI instances take the framework SSH key via metadata; '
            'the launch auth config carries none.')
    return ssh_keys.split(':', 1)[1]


def _state(inst: Dict[str, Any]) -> str:
    return str(inst.get('lifecycle-state', 'UNKNOWN')).upper()


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region  # the oci CLI profile pins the region
    node_cfg = config.node_config
    try:
        settings = _net_settings()
        existing = oci_cli.list_instances(cluster_name_on_cloud)
        running = [i for i in existing
                   if _state(i) in ('RUNNING', 'PROVISIONING',
                                    'STARTING')]
        stopped = [i for i in existing if _state(i) == 'STOPPED']

        resumed: List[str] = []
        if config.resume_stopped_nodes and stopped:
            need = config.count - len(running)
            for inst in sorted(stopped,
                               key=lambda i: str(i['id']))[
                    :max(need, 0)]:
                oci_cli.instance_action(str(inst['id']), 'START')
                resumed.append(str(inst['id']))
            running += [i for i in stopped
                        if str(i['id']) in resumed]

        created: List[str] = []
        to_create = config.count - len(running)
        if to_create > 0:
            shape, shape_config = parse_shape(
                node_cfg['instance_type'])
            pub = _public_key(config.authentication_config)
            base = len(existing)
            for i in range(to_create):
                inst = oci_cli.launch_instance(
                    name=f'{cluster_name_on_cloud}-{base + i:04d}',
                    shape=shape,
                    availability_domain=settings[
                        'availability_domain'],
                    subnet_id=settings['subnet_id'],
                    image_id=settings['image_id'],
                    ssh_authorized_keys=pub,
                    freeform_tags={'skytpu-cluster':
                                   cluster_name_on_cloud},
                    preemptible=bool(node_cfg.get('use_spot')),
                    shape_config=shape_config)
                created.append(str(inst.get('id')))
    except oci_cli.OciCliError as e:
        raise _classify(e) from None
    ids = sorted([str(i['id']) for i in running] + created)
    if not ids:
        raise exceptions.ResourcesUnavailableError(
            f'OCI returned no instances for {cluster_name_on_cloud}.')
    return common.ProvisionRecord(
        provider_name=_PROVIDER, cluster_name=cluster_name_on_cloud,
        region=oci_cli.config_value('region') or 'oci',
        zone=None, head_instance_id=ids[0],
        resumed_instance_ids=resumed, created_instance_ids=created)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    insts = [i for i in oci_cli.list_instances(cluster_name_on_cloud)
             if _state(i) in ('RUNNING', 'PROVISIONING', 'STARTING')]
    ids = sorted(str(i['id']) for i in insts)
    if worker_only and ids:
        ids = ids[1:]
    for iid in ids:
        oci_cli.instance_action(iid, 'STOP')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    ids = sorted(
        str(i['id'])
        for i in oci_cli.list_instances(cluster_name_on_cloud)
        if _state(i) not in ('TERMINATED', 'TERMINATING'))
    if worker_only and ids:
        ids = ids[1:]
    for iid in ids:
        oci_cli.terminate_instance(iid)


_STATUS_MAP = {
    'PROVISIONING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATING': 'terminated',
    'TERMINATED': 'terminated',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for inst in oci_cli.list_instances(cluster_name_on_cloud):
        status = _STATUS_MAP.get(_state(inst))
        if non_terminated_only and status == 'terminated':
            continue
        out[str(inst['id'])] = status
    return out


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: str = 'running', timeout: float = 600.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud, None,
                                   non_terminated_only=False)
        live = [s for s in statuses.values() if s != 'terminated']
        if live and all(s == state for s in live):
            return
        time.sleep(5)
    raise exceptions.ProvisionTimeoutError(
        f'{cluster_name_on_cloud}: instances did not reach {state!r} '
        f'within {timeout}s.')


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for inst in oci_cli.list_instances(cluster_name_on_cloud):
        if _state(inst) != 'RUNNING':
            continue
        iid = str(inst['id'])
        private, public = oci_cli.get_vnic_ips(iid)
        instances[iid] = [common.InstanceInfo(
            instance_id=iid,
            internal_ip=private or '',
            external_ip=public,
            tags=dict(inst.get('freeform-tags') or {}),
        )]
    head = sorted(instances)[0] if instances else None
    return common.ClusterInfo(
        instances=instances, head_instance_id=head,
        provider_name=_PROVIDER, provider_config=provider_config,
        ssh_user='ubuntu')


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.warning('OCI security-list automation is not implemented; '
                   'allow %s in the VCN console.', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('OCI security lists are not automated; nothing to close for %s.', ports)
