"""OCI control plane via the `oci` CLI (JSON output).

Counterpart of the reference's sky/provision/oci/* (oci SDK).  OCI
API requests need RSA request signing; rather than reimplement that,
the provisioner drives the official CLI — the exact pattern the OCI
object store already uses (data/storage.py OciStore).  `run` is the
single test seam.

Config: compartment from OCI_COMPARTMENT_ID / config
oci.compartment_id; subnet + image from config oci.subnet_id /
oci.image_id; region/auth from the standard ~/.oci/config profile.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

_OCI_CONFIG = '~/.oci/config'


class OciCliError(exceptions.ProvisionError):

    def __init__(self, returncode: int, message: str) -> None:
        no_failover = 'NotAuthenticated' in message or \
            'NotAuthorized' in message
        super().__init__(f'oci CLI error rc={returncode}: {message}',
                         no_failover=no_failover)
        self.returncode = returncode


def check_cli() -> Tuple[bool, Optional[str]]:
    if shutil.which('oci') is None:
        return False, ('`oci` CLI not found; install oci-cli and run '
                       '`oci setup config`.')
    if not os.path.exists(os.path.expanduser(
            os.environ.get('OCI_CLI_CONFIG_FILE', _OCI_CONFIG))):
        return False, ('~/.oci/config not found; run '
                       '`oci setup config`.')
    return True, None


def config_value(key: str) -> Optional[str]:
    path = os.path.expanduser(
        os.environ.get('OCI_CLI_CONFIG_FILE', _OCI_CONFIG))
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                m = re.match(rf'\s*{re.escape(key)}\s*=\s*(\S+)',
                             line.rstrip())
                if m:
                    return m.group(1)
    except OSError:
        return None
    return None


def compartment_id() -> str:
    from skypilot_tpu import config as config_lib
    comp = os.environ.get('OCI_COMPARTMENT_ID') or \
        config_lib.get_nested(('oci', 'compartment_id'), None) or \
        config_value('tenancy')  # root compartment fallback
    if not comp:
        raise exceptions.ProvisionError(
            'OCI needs a compartment: set OCI_COMPARTMENT_ID or '
            'config oci.compartment_id.')
    return comp


def run(args: List[str]) -> Any:
    """One `oci ...` invocation; parses JSON stdout."""
    proc = subprocess.run(['oci'] + args + ['--output', 'json'],
                          capture_output=True, text=True, check=False)
    if proc.returncode != 0:
        raise OciCliError(proc.returncode, proc.stderr[-500:])
    out = proc.stdout.strip()
    return json.loads(out) if out else {}


def launch_instance(name: str, shape: str, availability_domain: str,
                    subnet_id: str, image_id: str,
                    ssh_authorized_keys: str,
                    freeform_tags: Dict[str, str],
                    preemptible: bool = False,
                    shape_config: Optional[Dict[str, float]] = None
                    ) -> Dict[str, Any]:
    args = [
        'compute', 'instance', 'launch',
        '--compartment-id', compartment_id(),
        '--availability-domain', availability_domain,
        '--display-name', name,
        '--shape', shape,
        '--subnet-id', subnet_id,
        '--image-id', image_id,
        '--assign-public-ip', 'true',
        '--metadata', json.dumps(
            {'ssh_authorized_keys': ssh_authorized_keys}),
        '--freeform-tags', json.dumps(freeform_tags),
    ]
    if shape_config:
        args += ['--shape-config', json.dumps(shape_config)]
    if preemptible:
        args += ['--preemptible-instance-config',
                 json.dumps({'preemptionAction':
                             {'type': 'TERMINATE',
                              'preserveBootVolume': False}})]
    return dict(run(args).get('data') or {})


def list_instances(tag_value: str) -> List[Dict[str, Any]]:
    data = run(['compute', 'instance', 'list',
                '--compartment-id', compartment_id(),
                '--all']).get('data') or []
    return [i for i in data
            if (i.get('freeform-tags') or {}).get('skytpu-cluster')
            == tag_value]


def instance_action(instance_id: str, action: str) -> None:
    """START | STOP."""
    run(['compute', 'instance', 'action', '--instance-id',
         instance_id, '--action', action])


def terminate_instance(instance_id: str) -> None:
    run(['compute', 'instance', 'terminate', '--instance-id',
         instance_id, '--force'])


def get_vnic_ips(instance_id: str) -> Tuple[Optional[str],
                                            Optional[str]]:
    """(private_ip, public_ip) from the instance's attached VNICs."""
    data = run(['compute', 'instance', 'list-vnics',
                '--instance-id', instance_id]).get('data') or []
    for vnic in data:
        if vnic.get('is-primary', True):
            return vnic.get('private-ip'), vnic.get('public-ip')
    return None, None
