"""Local provisioner: clusters as directories + processes on this machine.

A "cluster" is <state_dir>/local_clusters/<cluster_name>/ with one sub-root
per simulated host (node<N>/host<K>/).  Host addresses are 'local:<dir>'
URIs; the CommandRunner layer resolves them to process execution with the
host dir as HOME-like root, so the entire backend/agent/gang-exec stack
runs unchanged against local clusters.  This is the hermetic end-to-end
substrate the reference lacks (its cheapest real substrate is Kubernetes,
SURVEY.md §4) and doubles as `sky local`-style laptop/TPU-VM usage.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.utils import paths

logger = sky_logging.init_logger(__name__)

_PROVIDER = 'local'


def _cluster_dir(cluster_name_on_cloud: str) -> str:
    return os.path.join(paths.local_clusters_dir(), cluster_name_on_cloud)


def _meta_path(cluster_name_on_cloud: str) -> str:
    return os.path.join(_cluster_dir(cluster_name_on_cloud), 'cluster.json')


def _load_meta(cluster_name_on_cloud: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_meta_path(cluster_name_on_cloud), encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _save_meta(cluster_name_on_cloud: str, meta: Dict[str, Any]) -> None:
    os.makedirs(_cluster_dir(cluster_name_on_cloud), exist_ok=True)
    with open(_meta_path(cluster_name_on_cloud), 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=2)


def host_address(cluster_name_on_cloud: str, node: int, host: int) -> str:
    return 'local:' + os.path.join(_cluster_dir(cluster_name_on_cloud),
                                   f'node{node}', f'host{host}')


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    num_hosts = int(node_cfg.get('num_tpu_hosts', 1) or 1)
    meta = _load_meta(cluster_name_on_cloud)
    created: List[str] = []
    resumed: List[str] = []
    if meta is None:
        meta = {
            'cluster': cluster_name_on_cloud,
            'num_nodes': config.count,
            'num_hosts_per_node': num_hosts,
            'status': 'running',
            'created_at': time.time(),
            'tags': dict(config.tags),
        }
        for node in range(config.count):
            for host in range(num_hosts):
                host_dir = host_address(cluster_name_on_cloud, node,
                                        host)[len('local:'):]
                os.makedirs(os.path.join(host_dir, '.skytpu_agent'),
                            exist_ok=True)
            created.append(f'{cluster_name_on_cloud}-node{node}')
    else:
        if meta['status'] == 'stopped':
            if not config.resume_stopped_nodes:
                from skypilot_tpu import exceptions
                raise exceptions.ProvisionError(
                    f'Local cluster {cluster_name_on_cloud} is stopped; '
                    'resume not requested.')
            meta['status'] = 'running'
            resumed = [f'{cluster_name_on_cloud}-node{n}'
                       for n in range(meta['num_nodes'])]
        meta['num_nodes'] = max(meta['num_nodes'], config.count)
    _save_meta(cluster_name_on_cloud, meta)
    return common.ProvisionRecord(
        provider_name=_PROVIDER,
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone='local',
        head_instance_id=f'{cluster_name_on_cloud}-node0',
        resumed_instance_ids=resumed,
        created_instance_ids=created,
    )


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    del worker_only
    meta = _load_meta(cluster_name_on_cloud)
    if meta is not None:
        meta['status'] = 'stopped'
        _save_meta(cluster_name_on_cloud, meta)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del worker_only
    # Kill any agent/job processes rooted in this cluster dir first.
    cluster_dir = _cluster_dir(cluster_name_on_cloud)
    _kill_cluster_processes(cluster_dir)
    shutil.rmtree(cluster_dir, ignore_errors=True)


def _kill_cluster_processes(cluster_dir: str) -> None:
    try:
        import psutil
    except ImportError:
        return
    for proc in psutil.process_iter(['pid', 'environ']):
        try:
            env = proc.info['environ'] or {}
            if env.get('SKYTPU_LOCAL_HOST_ROOT', '').startswith(cluster_dir):
                proc.kill()
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            continue


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    meta = _load_meta(cluster_name_on_cloud)
    if meta is None:
        return {}
    status = meta['status']
    if non_terminated_only and status == 'terminated':
        return {}
    return {f'{cluster_name_on_cloud}-node{n}': status
            for n in range(meta['num_nodes'])}


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region
    meta = _load_meta(cluster_name_on_cloud)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    if meta is not None and meta['status'] == 'running':
        num_hosts = meta.get('num_hosts_per_node', 1)
        for node in range(meta['num_nodes']):
            iid = f'{cluster_name_on_cloud}-node{node}'
            host_ips = [host_address(cluster_name_on_cloud, node, h)
                        for h in range(num_hosts)]
            instances[iid] = [
                common.InstanceInfo(
                    instance_id=iid,
                    internal_ip=host_ips[0],
                    external_ip=None,
                    tags=meta.get('tags', {}),
                    host_ips=host_ips,
                )
            ]
        head_id = f'{cluster_name_on_cloud}-node0'
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name=_PROVIDER,
        provider_config=provider_config,
        ssh_user=None,
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    # Local 'nodes' share the host's network namespace: a port a
    # process binds is already reachable — nothing to program.
    logger.info('local cloud: ports %s ride the host network '
                '(no firewall layer to open).', ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, provider_config
    logger.info('local cloud: nothing to close for ports %s.', ports)
